"""KNN imputation, CV fold replication, and SVC training parity."""

import warnings

import numpy as np
import pytest

import jax.numpy as jnp

from machine_learning_replications_tpu.models import knn_impute, scaler, svm
from machine_learning_replications_tpu.utils import (
    kfold_test_masks,
    stratified_kfold_test_masks,
)


def test_kfold_masks_match_sklearn():
    from sklearn.model_selection import KFold

    for n, k in [(1427, 10), (713, 5), (100, 7)]:
        ours = kfold_test_masks(n, k)
        for i, (_, test) in enumerate(KFold(k).split(np.zeros((n, 1)))):
            np.testing.assert_array_equal(np.where(ours[i])[0], test)


def test_stratified_kfold_masks_match_sklearn():
    from sklearn.model_selection import StratifiedKFold

    rng = np.random.default_rng(0)
    for n, k in [(713, 5), (500, 5), (101, 3)]:
        y = (rng.random(n) < 0.2).astype(float)
        ours = stratified_kfold_test_masks(y, k)
        for i, (_, test) in enumerate(StratifiedKFold(k).split(np.zeros((n, 1)), y)):
            np.testing.assert_array_equal(np.where(ours[i])[0], test)


def test_knn_impute_matches_sklearn(cohort):
    from sklearn.impute import KNNImputer

    X, _, _ = cohort  # has 5% MCAR missingness in non-binary columns
    sk = KNNImputer(missing_values=np.nan, n_neighbors=1, copy=True)
    X_sk = sk.fit_transform(X)
    params, X_ours = knn_impute.fit_transform(jnp.asarray(X))
    np.testing.assert_allclose(np.asarray(X_ours), X_sk, rtol=1e-12, atol=1e-12)


def test_knn_impute_complete_donor_columns_share_argmin(cohort):
    """The specialised block fn routes donor-complete columns straight to
    the global top-1 neighbor (``_block_fn``'s unmasked branch) — sklearn
    parity must hold when the fit cohort is fully observed and only
    queries have NaN, and in the mixed case (some donor columns NaN, some
    complete)."""
    from sklearn.impute import KNNImputer
    from machine_learning_replications_tpu.data import make_cohort

    X, _, _ = cohort                      # donors WITH missingness (mixed)
    X_full = np.asarray(X)
    X_complete = np.where(np.isnan(X_full), np.nanmean(X_full, axis=0), X_full)
    Xq, _, _ = make_cohort(n=150, seed=31, missing_rate=0.10)

    # all-shared: every donor column complete
    sk = KNNImputer(n_neighbors=1).fit(X_complete)
    params = knn_impute.fit(jnp.asarray(X_complete))
    np.testing.assert_allclose(
        np.asarray(knn_impute.transform(params, jnp.asarray(Xq))),
        sk.transform(np.asarray(Xq)), rtol=1e-12, atol=1e-12,
    )

    # mixed: NaN donors in some query-NaN columns, complete in others
    X_mixed = np.array(X_full)
    nan_cols = np.flatnonzero(np.isnan(X_full).any(axis=0))
    fixed = nan_cols[: len(nan_cols) // 2]
    X_mixed[:, fixed] = np.where(
        np.isnan(X_full[:, fixed]),
        np.nanmean(X_full[:, fixed], axis=0),
        X_full[:, fixed],
    )
    sk2 = KNNImputer(n_neighbors=1).fit(X_mixed)
    params2 = knn_impute.fit(jnp.asarray(X_mixed))
    np.testing.assert_allclose(
        np.asarray(knn_impute.transform(params2, jnp.asarray(Xq))),
        sk2.transform(np.asarray(Xq)), rtol=1e-12, atol=1e-12,
    )


def test_block_fn_specialisation_resolution(cohort):
    """_block_fn_for derives nan_cols from the query and the masked subset
    from the donor matrix: donor-complete columns must NOT be in the
    masked set (they ride the top-1 branch), and fully-observed query
    columns must not appear at all."""
    import numpy as np

    X, _, _ = cohort
    X_np = np.asarray(X)
    params = knn_impute.fit(jnp.asarray(X_np))
    q_nan_cols = set(np.flatnonzero(np.isnan(X_np).any(axis=0)))
    donor_nan_cols = set(
        np.flatnonzero(np.isnan(np.asarray(params.donors)).any(axis=0))
    )

    captured = {}
    orig = knn_impute._block_fn

    def spy(nan_cols, masked, dist_cols=None):
        captured["nan_cols"], captured["masked"] = nan_cols, masked
        captured["dist_cols"] = dist_cols
        return orig(nan_cols, masked, dist_cols)

    knn_impute._block_fn, _restore = spy, orig
    try:
        knn_impute._block_fn_for(params, X_np)
    finally:
        knn_impute._block_fn = _restore

    assert set(captured["nan_cols"]) == q_nan_cols
    assert set(captured["masked"]) == q_nan_cols & donor_nan_cols
    # Partial missingness (NaN columns still hold some values): the
    # restricted-distance specialisation must NOT engage.
    assert captured["dist_cols"] is None

    # complete donors -> empty masked set even when queries have NaN
    X_complete = np.where(np.isnan(X_np), np.nanmean(X_np, axis=0), X_np)
    p2 = knn_impute.fit(jnp.asarray(X_complete))
    knn_impute._block_fn = spy
    try:
        knn_impute._block_fn_for(p2, X_np)
    finally:
        knn_impute._block_fn = _restore
    assert captured["masked"] == ()
    assert set(captured["nan_cols"]) == q_nan_cols


def test_block_fn_fully_missing_columns_fast_path(cohort):
    """The contract-row shape (every NaN column fully missing) engages the
    restricted-distance + per-column-argmin specialisation; its output
    must be BIT-identical to the unrestricted top-K form — the imputed
    values are copied donor values, so identical selections mean
    identical bytes (the bulk-scoring / serving parity contract)."""
    import numpy as np

    X, _, _ = cohort
    X_np = np.asarray(X)
    params = knn_impute.fit(jnp.asarray(X_np))
    # Build a contract-like query block: values only in 17 columns, the
    # other 47 fully NaN.
    rng = np.random.default_rng(3)
    keep = np.sort(rng.choice(X_np.shape[1], size=17, replace=False))
    Xq = np.full((64, X_np.shape[1]), np.nan)
    Xq[:, keep] = np.nan_to_num(X_np[:64, keep], nan=1.0)
    nan_cols = tuple(int(c) for c in np.flatnonzero(np.isnan(Xq).any(axis=0)))
    donor_nan = np.isnan(np.asarray(params.donors)).any(axis=0)
    masked = tuple(int(c) for c in nan_cols if donor_nan[c])
    resolved = knn_impute._block_fn_for(params, Xq)
    # The specialisation engaged (cache key includes dist_cols).
    assert resolved is knn_impute._block_fn(nan_cols, masked, tuple(
        int(c) for c in keep
    ))
    full = np.asarray(
        knn_impute._block_fn(nan_cols, masked, None)(params, jnp.asarray(Xq))
    )
    fast = np.asarray(resolved(params, jnp.asarray(Xq)))
    np.testing.assert_array_equal(fast, full)
    # And both match the brute-force sklearn-semantics oracle.
    oracle = _impute_oracle(
        np.asarray(params.donors), np.asarray(params.col_means), Xq
    )
    np.testing.assert_allclose(fast, oracle, rtol=0, atol=0)


def _impute_oracle(donors, col_means, Xq):
    """Brute-force sklearn-semantics 1-NN imputation: full masked distance
    scan per feature, first-index tie-break — the spec the top-K fast path
    plus cond-gated fallback must reproduce exactly."""
    nd, F = donors.shape
    out = np.array(Xq, dtype=float)
    for i in range(Xq.shape[0]):
        q = Xq[i]
        qm = ~np.isnan(q)
        d = np.full(nd, np.inf)
        for j in range(nd):
            m = qm & ~np.isnan(donors[j])
            if m.any():
                diff = q[m] - donors[j][m]
                d[j] = (diff @ diff) * F / m.sum()
        for f in range(F):
            if not np.isnan(q[f]):
                continue
            df = np.where(~np.isnan(donors[:, f]), d, np.inf)
            jmin = int(np.argmin(df))  # first index among ties
            out[i, f] = donors[jmin, f] if np.isfinite(df[jmin]) \
                else col_means[f]
    return out


def test_knn_impute_topk_matches_bruteforce_oracle():
    """Randomized differential: many NaN patterns (incl. donor pools
    smaller than K=8, high missingness forcing the exact fallback, and
    tie-heavy integer-valued features) against the brute-force oracle."""
    rng = np.random.default_rng(404)
    for trial in range(12):
        nd = int(rng.integers(3, 40))
        nq = int(rng.integers(2, 25))
        F = int(rng.integers(2, 9))
        # integer-valued features make distance ties common
        donors = rng.integers(0, 3, size=(nd, F)).astype(float)
        Xq = rng.integers(0, 3, size=(nq, F)).astype(float)
        miss_d = rng.random(size=donors.shape) < rng.uniform(0.05, 0.5)
        miss_q = rng.random(size=Xq.shape) < rng.uniform(0.1, 0.6)
        donors[miss_d] = np.nan
        Xq[miss_q] = np.nan
        donors[0, :] = 0.0  # keep at least one complete donor row
        col_means = np.nanmean(donors, axis=0)  # same quantity fit() uses
        params = knn_impute.KNNImputerParams(
            donors=jnp.asarray(donors),
            col_means=jnp.asarray(col_means),
        )
        ours = np.asarray(knn_impute.transform(params, jnp.asarray(Xq)))
        oracle = _impute_oracle(donors, col_means, Xq)
        np.testing.assert_allclose(ours, oracle, rtol=1e-12, atol=1e-12,
                                   err_msg=f"trial {trial}")


def test_knn_impute_transform_other_cohort(cohort):
    from sklearn.impute import KNNImputer
    from machine_learning_replications_tpu.data import make_cohort

    X, _, _ = cohort
    X2, _, _ = make_cohort(n=200, seed=77, missing_rate=0.08)
    sk = KNNImputer(n_neighbors=1).fit(X)
    params = knn_impute.fit(jnp.asarray(X))
    np.testing.assert_allclose(
        np.asarray(knn_impute.transform(params, jnp.asarray(X2))),
        sk.transform(X2),
        rtol=1e-12,
        atol=1e-12,
    )


@pytest.fixture(scope="module")
def svc_data():
    rng = np.random.default_rng(21)
    n, f = 350, 17
    X = rng.normal(size=(n, f))
    w = rng.normal(size=f)
    y = (X @ w + 1.2 * rng.normal(size=n) > 0.8).astype(float)  # ~20% positive
    return X, y


def test_svc_fit_decision_parity(svc_data):
    from sklearn.svm import SVC

    X, y = svc_data
    sp = scaler.fit(jnp.asarray(X))
    Xt = scaler.transform(sp, jnp.asarray(X))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        sk = SVC(class_weight="balanced", probability=True, random_state=2020).fit(
            np.asarray(Xt), y
        )
    ours = svm.svc_fit(Xt, jnp.asarray(y), tol=1e-7, max_iter=4000)
    np.testing.assert_allclose(float(ours.gamma), sk._gamma, rtol=1e-9)

    dec_sk = sk.decision_function(np.asarray(Xt))
    dec_us = np.asarray(svm.decision_function(ours, Xt))
    # libsvm stops at KKT tol 1e-3; demand matching decisions to ~1e-3
    assert np.abs(dec_sk - dec_us).max() < 5e-3, np.abs(dec_sk - dec_us).max()
    np.testing.assert_allclose(float(ours.intercept), sk.intercept_[0], atol=5e-3)

    # support vector pattern: nonzero coefs agree (up to boundary wobble)
    sk_sv = np.zeros(len(y), bool)
    sk_sv[sk.support_] = True
    our_sv = np.abs(np.asarray(ours.dual_coef)) > 1e-6
    assert (sk_sv ^ our_sv).mean() < 0.03

    # Platt: same sign structure and close calibration
    assert float(ours.prob_a) < 0
    # probability predictions close at the metric level
    p_sk = sk.predict_proba(np.asarray(Xt))[:, 1]
    p_us = np.asarray(svm.predict_proba1(ours, Xt))
    assert np.abs(p_sk - p_us).max() < 0.05
    assert np.corrcoef(p_sk, p_us)[0, 1] > 0.999


def test_trim_support(svc_data):
    X, y = svc_data
    sp = scaler.fit(jnp.asarray(X))
    Xt = scaler.transform(sp, jnp.asarray(X))
    full = svm.svc_fit(Xt, jnp.asarray(y), probability=False, tol=1e-7, max_iter=2000)
    trimmed = svm.trim_support(full)
    assert trimmed.support_vectors.shape[0] < Xt.shape[0]
    np.testing.assert_allclose(
        np.asarray(svm.decision_function(trimmed, Xt)),
        np.asarray(svm.decision_function(full, Xt)),
        rtol=1e-9,
        atol=1e-9,
    )


def test_transform_complete_rows_pass_through_unchanged():
    """The incomplete-row pre-filter must be semantically invisible: mixed
    cohorts impute identically to the all-rows path, complete rows are
    returned bit-for-bit, and an all-complete cohort short-circuits."""
    import jax.numpy as jnp

    from machine_learning_replications_tpu.models import knn_impute

    rng = np.random.default_rng(17)
    Xf = rng.normal(size=(120, 6))
    params = knn_impute.fit(jnp.asarray(Xf))

    Xq = rng.normal(size=(40, 6))
    Xq[5, 2] = np.nan
    Xq[17, 0] = np.nan
    out = np.asarray(knn_impute.transform(params, jnp.asarray(Xq)))
    # complete rows bit-identical
    complete = ~np.isnan(Xq).any(axis=1)
    np.testing.assert_array_equal(out[complete], Xq[complete])
    # incomplete rows match imputing them alone (the pre-filter's route)
    alone = np.asarray(knn_impute.transform(params, jnp.asarray(Xq[~complete])))
    np.testing.assert_array_equal(out[~complete], alone)
    assert np.isfinite(out).all()
    # all-complete short-circuit
    np.testing.assert_array_equal(
        np.asarray(knn_impute.transform(params, jnp.asarray(Xq[complete]))),
        Xq[complete],
    )
