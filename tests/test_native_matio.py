"""Native C++ MAT-v5 reader vs the scipy oracle (SURVEY.md §2.4 row
"scipy.io.loadmat"). Skips when the toolchain can't produce the library."""

import numpy as np
import pytest
import scipy.io as sio

from machine_learning_replications_tpu.data import load_data, make_cohort, save_data
from machine_learning_replications_tpu.native import matio


@pytest.fixture(scope="module")
def native_available():
    if matio.read_mat_vars.__module__ and matio._load() is None:
        pytest.skip("native matio library unavailable (no toolchain)")
    return True


def test_matches_scipy_plain_and_compressed(tmp_path, native_available):
    X, y, names = make_cohort(n=64, seed=7)
    plain = tmp_path / "plain.mat"
    save_data(str(plain), X, y, names)
    comp = tmp_path / "comp.mat"
    sio.savemat(
        str(comp),
        {"data_tb": np.hstack([X, y[:, None]]), "clin_var_names": names},
        do_compression=True,
    )
    ref = sio.loadmat(str(plain))
    for path in (plain, comp):
        out = matio.read_mat_vars(str(path), ["data_tb", "clin_var_names"])
        np.testing.assert_array_equal(out["data_tb"], ref["data_tb"])
        assert out["clin_var_names"].shape == ref["clin_var_names"].shape
        assert list(out["clin_var_names"][0]) == [
            str(s[0]) for s in ref["clin_var_names"][0]
        ]


def test_numeric_storage_type_promotion(tmp_path, native_available):
    """MATLAB stores small-valued doubles in narrow int types; all must
    promote to float64 exactly."""
    arrs = {
        "data_tb": np.arange(12, dtype=np.float64).reshape(3, 4),
        "clin_var_names": np.array([["a", "bb", "ccc"]], dtype=object),
    }
    p = tmp_path / "narrow.mat"
    sio.savemat(str(p), arrs)  # scipy narrows integral doubles on write
    out = matio.read_mat_vars(str(p), ["data_tb", "clin_var_names"])
    np.testing.assert_array_equal(out["data_tb"], arrs["data_tb"])
    assert out["data_tb"].dtype == np.float64


def test_fortran_order_roundtrip(tmp_path, native_available):
    """Column-major payload must come back as the original row-major view."""
    X = np.arange(20, dtype=np.float64).reshape(4, 5)
    p = tmp_path / "f.mat"
    sio.savemat(str(p), {"data_tb": X, "clin_var_names": np.array([["x"]], object)})
    out = matio.read_mat_vars(str(p), ["data_tb"])
    np.testing.assert_array_equal(out["data_tb"], X)


def test_missing_variable_raises(tmp_path, native_available):
    p = tmp_path / "m.mat"
    sio.savemat(str(p), {"other": np.ones((2, 2))})
    with pytest.raises(KeyError):
        matio.read_mat_vars(str(p), ["data_tb"])


def test_not_a_mat_file(tmp_path, native_available):
    p = tmp_path / "garbage.mat"
    p.write_bytes(b"this is not a mat file")
    with pytest.raises(OSError):
        matio.read_mat_vars(str(p), ["data_tb"])


def test_load_data_backend_equivalence(tmp_path, native_available):
    X, y, names = make_cohort(n=40, seed=3, missing_rate=0.05)
    p = tmp_path / "c.mat"
    save_data(str(p), X, y, names)
    Xn, yn, _ = load_data(str(p), backend="native")
    Xs, ys, _ = load_data(str(p), backend="scipy")
    np.testing.assert_array_equal(
        np.asarray(Xn, dtype=np.float64), np.asarray(Xs, dtype=np.float64)
    )
    np.testing.assert_array_equal(yn, ys)
