"""Native C++ MAT-v5 reader vs the scipy oracle (SURVEY.md §2.4 row
"scipy.io.loadmat"). Skips when the toolchain can't produce the library."""

import os
import numpy as np
import pytest
import scipy.io as sio

from machine_learning_replications_tpu.data import load_data, make_cohort, save_data
from machine_learning_replications_tpu.native import matio


@pytest.fixture(scope="module")
def native_available():
    if matio._load() is None:
        pytest.skip("native matio library unavailable (no toolchain)")
    return True


def test_matches_scipy_plain_and_compressed(tmp_path, native_available):
    X, y, names = make_cohort(n=64, seed=7)
    plain = tmp_path / "plain.mat"
    save_data(str(plain), X, y, names)
    comp = tmp_path / "comp.mat"
    sio.savemat(
        str(comp),
        {"data_tb": np.hstack([X, y[:, None]]), "clin_var_names": names},
        do_compression=True,
    )
    ref = sio.loadmat(str(plain))
    for path in (plain, comp):
        out = matio.read_mat_vars(str(path), ["data_tb", "clin_var_names"])
        np.testing.assert_array_equal(out["data_tb"], ref["data_tb"])
        assert out["clin_var_names"].shape == ref["clin_var_names"].shape
        assert list(out["clin_var_names"][0]) == [
            str(s[0]) for s in ref["clin_var_names"][0]
        ]


def _mat5_numeric(name: bytes, mi_type: int, payload: bytes, dims=(2, 3),
                  mx_class: int = 6) -> bytes:
    """Hand-craft a minimal MAT-5 file with one numeric miMATRIX whose data
    subelement uses storage type ``mi_type`` (MATLAB narrows integral
    doubles on write; scipy does not, so this path must be built by hand)."""
    import struct

    def element(t, data):
        pad = (8 - len(data) % 8) % 8
        return struct.pack("<II", t, len(data)) + data + b"\0" * pad

    flags = element(6, struct.pack("<II", mx_class, 0))          # miUINT32 ×2
    dim_e = element(5, struct.pack("<ii", *dims))                # miINT32
    name_e = element(1, name)                                    # miINT8
    data_e = element(mi_type, payload)
    matrix = element(14, flags + dim_e + name_e + data_e)
    header = b"MATLAB 5.0 MAT-file, handcrafted".ljust(124) + struct.pack(
        "<HH", 0x0100, 0x4D49
    )
    return header + matrix


@pytest.mark.parametrize(
    "mi_type,np_dtype",
    [(1, np.int8), (2, np.uint8), (3, np.int16), (4, np.uint16),
     (5, np.int32), (7, np.float32), (9, np.float64)],
)
def test_numeric_storage_type_promotion(tmp_path, native_available, mi_type, np_dtype):
    """Every storage type MATLAB may narrow doubles into must promote back
    to exact float64 (column-major payload)."""
    vals = np.array([[0, 1, 2], [3, 4, 5]], dtype=np_dtype)
    p = tmp_path / f"narrow{mi_type}.mat"
    p.write_bytes(
        _mat5_numeric(b"data_tb", mi_type, vals.tobytes(order="F"))
    )
    out = matio.read_mat_vars(str(p), ["data_tb"])
    np.testing.assert_array_equal(out["data_tb"], vals.astype(np.float64))
    assert out["data_tb"].dtype == np.float64


def test_fortran_order_roundtrip(tmp_path, native_available):
    """Column-major payload must come back as the original row-major view."""
    X = np.arange(20, dtype=np.float64).reshape(4, 5)
    p = tmp_path / "f.mat"
    sio.savemat(str(p), {"data_tb": X, "clin_var_names": np.array([["x"]], object)})
    out = matio.read_mat_vars(str(p), ["data_tb"])
    np.testing.assert_array_equal(out["data_tb"], X)


def test_missing_variable_raises(tmp_path, native_available):
    p = tmp_path / "m.mat"
    sio.savemat(str(p), {"other": np.ones((2, 2))})
    with pytest.raises(KeyError):
        matio.read_mat_vars(str(p), ["data_tb"])


def test_not_a_mat_file(tmp_path, native_available):
    p = tmp_path / "garbage.mat"
    p.write_bytes(b"this is not a mat file")
    with pytest.raises(OSError):
        matio.read_mat_vars(str(p), ["data_tb"])


def test_load_data_backend_equivalence(tmp_path, native_available):
    X, y, names = make_cohort(n=40, seed=3, missing_rate=0.05)
    p = tmp_path / "c.mat"
    save_data(str(p), X, y, names)
    Xn, yn, _ = load_data(str(p), backend="native")
    Xs, ys, _ = load_data(str(p), backend="scipy")
    np.testing.assert_array_equal(
        np.asarray(Xn, dtype=np.float64), np.asarray(Xs, dtype=np.float64)
    )
    np.testing.assert_array_equal(yn, ys)


def test_unwritable_package_dir_falls_back_to_user_cache(
    tmp_path, monkeypatch, native_available
):
    """Packaged installs can land the package dir read-only; the build must
    fall back to the per-user cache path and still produce a loadable lib.
    ``native_available`` keeps the file's skip contract on toolchain-less
    hosts; the delenv guards against an ambient opt-out."""
    monkeypatch.delenv("MLR_TPU_NO_NATIVE", raising=False)
    # Point the preferred target somewhere no process can create files.
    monkeypatch.setattr(matio, "_SO", "/proc/nonexistent/_matio.so")
    monkeypatch.setattr(matio, "_lib_cache", [])
    cached = matio._cache_so()
    assert cached is not None
    if os.path.exists(cached):
        os.unlink(cached)
    lib = matio._load()
    assert lib is not None, "fallback build did not produce a loadable lib"
    assert os.path.exists(cached)
    mode = os.lstat(os.path.dirname(cached)).st_mode & 0o777
    assert mode == 0o700
