"""Scaled-regime guards (SURVEY.md §7 "SVC on TPU"; VERDICT.md round-1
item 7): the O(n²) SVC kernel and O(n_q·n_fit) KNN donor matrix must not
silently OOM at BASELINE config-5 scale — above the configured thresholds
the SVC member subsamples (or refuses, per policy) and the imputer caps its
donor cohort and chunks its queries. Thresholds here are tiny so the tests
exercise the guard paths, not the memory they exist to bound."""

import numpy as np
import jax.numpy as jnp
import pytest

from machine_learning_replications_tpu.config import (
    ExperimentConfig,
    GBDTConfig,
    ImputerConfig,
    SVCConfig,
)
from machine_learning_replications_tpu.data.schema import selected_indices
from machine_learning_replications_tpu.models import knn_impute, pipeline, stacking, svm
from machine_learning_replications_tpu.utils.cv import stratified_subsample_indices


@pytest.fixture(scope="module")
def xy17(cohort_full):
    X, y, _ = cohort_full
    return np.asarray(X[:360, selected_indices()]), np.asarray(y[:360])


def test_stratified_subsample_deterministic_and_stratified():
    y = np.r_[np.zeros(80), np.ones(20)]
    idx = stratified_subsample_indices(y, 50, seed=7)
    assert idx.shape == (50,) and (idx == stratified_subsample_indices(y, 50, seed=7)).all()
    assert y[idx].sum() == 10  # 20% positives preserved exactly
    rows = np.arange(30, 100)  # restricted pool
    idx2 = stratified_subsample_indices(y, 40, rows=rows, seed=7)
    assert np.isin(idx2, rows).all() and idx2.shape == (40,)
    # m >= pool → identity
    assert (stratified_subsample_indices(y, 200, rows=rows) == np.sort(rows)).all()


def test_svc_scale_policy_error_message(xy17):
    X, y = xy17
    cfg = ExperimentConfig(
        svc=SVCConfig(max_rows=100, scale_policy="error"),
        gbdt=GBDTConfig(n_estimators=3),
    )
    with pytest.raises(RuntimeError, match="O\\(n²\\)|max_rows"):
        pipeline.fit_stacking(X, y, cfg)


def test_svc_subsample_policy_fits_and_tracks_full(xy17):
    """fit_stacking beyond the SVC threshold completes via the subsample
    path and its predictions stay close to the unguarded fit (the SVC
    member is the only one subsampled, and 240 of 360 rows retain most of
    the information)."""
    from machine_learning_replications_tpu.utils import metrics

    X, y = xy17
    base = ExperimentConfig(
        svc=SVCConfig(platt_cv=2), gbdt=GBDTConfig(n_estimators=10)
    )
    guarded_cfg = ExperimentConfig(
        svc=SVCConfig(platt_cv=2, max_rows=240), gbdt=GBDTConfig(n_estimators=10)
    )
    full = pipeline.fit_stacking(X, y, base)
    guarded = pipeline.fit_stacking(X, y, guarded_cfg)
    # the guarded SVC support set is the subsample
    assert guarded.svc.support_vectors.shape[0] == 240
    p_full = np.asarray(stacking.predict_proba1(full, X))
    p_guard = np.asarray(stacking.predict_proba1(guarded, X))
    auc_full = float(metrics.roc_auc(y, p_full))
    auc_guard = float(metrics.roc_auc(y, p_guard))
    assert abs(auc_full - auc_guard) < 0.05, (auc_full, auc_guard)


def test_svc_chunked_predict_matches_single_shot(xy17):
    X, y = xy17
    Xt = jnp.asarray((X - X.mean(0)) / (X.std(0) + 1e-9))
    params = svm.svc_fit(Xt, jnp.asarray(y), platt_cv=2, max_iter=800)
    whole = np.asarray(svm.predict_proba1(params, Xt))
    chunked = svm.predict_proba1_chunked(params, np.asarray(Xt), chunk_rows=100)
    np.testing.assert_allclose(chunked, whole, rtol=1e-6, atol=1e-9)


def test_knn_donor_cap_and_chunked_transform(cohort):
    X, y, _ = cohort  # 500 rows, 5% missing
    cfg = ImputerConfig(max_donors=200, chunk_rows=128)
    params = knn_impute.fit(jnp.asarray(X), cfg, seed=11)
    assert params.donors.shape[0] == 200
    out_chunked = np.asarray(knn_impute.transform(params, jnp.asarray(X), cfg.chunk_rows))
    out_single = np.asarray(knn_impute.transform(params, jnp.asarray(X), 10_000))
    np.testing.assert_array_equal(out_chunked, out_single)
    assert not np.isnan(out_chunked).any()
    # observed entries pass through untouched
    obs = ~np.isnan(X)
    np.testing.assert_array_equal(out_chunked[obs], X[obs])


def test_scaled_cross_val_meta_features_valid(xy17):
    """The subsampled out-of-fold SVC path: probabilities in (0, 1), every
    row covered by exactly its own test fold."""
    X, y = xy17
    cfg = ExperimentConfig(
        svc=SVCConfig(platt_cv=2, max_rows=200, predict_chunk_rows=64),
        gbdt=GBDTConfig(n_estimators=5),
    )
    meta = pipeline.cross_val_member_probas(X, y, cfg)
    assert meta.shape == (X.shape[0], 3)
    assert ((meta > 0) & (meta < 1)).all()


def test_exact_stump_layout_guard_and_member_cap(monkeypatch):
    """The exact splitter's candidate set is unbounded on continuous
    columns (~n unique midpoints); at 2M rows the depth-1 layout's
    B-scaled intermediates OOM'd multi-TB allocations (r5). Two defenses:
    gbdt.fit refuses with sizing advice when the estimated layout exceeds
    its budget, and the pipeline's full-data member fit switches to the
    capped hist protocol at device-binning scale."""
    import numpy as np
    import pytest

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.models import gbdt

    # policy assertions first — the guard check below shrinks the module
    # budget that scaled_member_cfg also reads
    cfg = GBDTConfig(splitter="exact")
    assert gbdt.scaled_member_cfg(cfg, 20_000, 17).splitter == "exact"
    scaled = gbdt.scaled_member_cfg(cfg, gbdt.DEVICE_BINNING_MIN_ROWS, 17)
    assert scaled.splitter == "hist"
    assert scaled.n_estimators == cfg.n_estimators  # only the splitter moves
    # below the scale gate, a worst-case layout estimate past the budget
    # ALSO switches (the region where fit() would otherwise refuse)
    assert gbdt.scaled_member_cfg(cfg, 60_000, 25).splitter == "hist"
    # hist configs pass through untouched at any size, and depth>=2 exact
    # is already quantile-capped so it passes through too
    hist_cfg = GBDTConfig(splitter="hist")
    assert gbdt.scaled_member_cfg(hist_cfg, 10**7, 17) is hist_cfg
    deep = GBDTConfig(splitter="exact", max_depth=2)
    assert gbdt.scaled_member_cfg(deep, 10**7, 17) is deep
    # the guard override threads through fit()
    rng2 = np.random.default_rng(1)
    X2 = rng2.normal(size=(4000, 3))
    y2 = (X2[:, 0] > 0).astype(float)
    params, _ = gbdt.fit(
        X2, y2, GBDTConfig(n_estimators=2, splitter="exact"),
        max_layout_bytes=1 << 34,
    )

    rng = np.random.default_rng(0)
    X = rng.normal(size=(4000, 3))  # continuous → ~4000 candidates/column
    y = (X[:, 0] > 0).astype(float)
    monkeypatch.setattr(gbdt, "_STUMP_LAYOUT_BYTES_BUDGET", 1 << 10)
    with pytest.raises(RuntimeError, match="splitter='hist'"):
        gbdt.fit(X, y, GBDTConfig(n_estimators=2, splitter="exact"))
