"""Serving layer (serve/): parity, compile-cache bound, flush policy,
admission control, graceful drain, HTTP front end, and the load generator.

The acceptance contract (ISSUE 1): served probabilities identical to the
single-patient CLI path, at most one XLA compile per bucket size, a
bounded queue with measured shed behavior under overload, and p50/p95/p99
+ throughput in a SERVE_BENCH artifact. Everything here is CPU-runnable
under the tier-1 marker set; the shipped-pickle leg (printing 27.09 %)
skips where the reference artifact is absent, and a live sklearn-imported
ensemble covers the same route unconditionally.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

from machine_learning_replications_tpu.data.examples import (
    EXAMPLE_PATIENT,
    patient_row,
)
from machine_learning_replications_tpu.serve import (
    BucketedPredictEngine,
    MicroBatcher,
    Overloaded,
    ServingMetrics,
    make_server,
)

_HAVE_REFERENCE_PKL = os.path.exists(
    "/root/reference/Machine Learning for Predicting Heart Failure "
    "Progression/hf_predict_model.pkl"
)


@pytest.fixture(scope="module")
def stacking_params():
    """A live sklearn-fitted stacking ensemble imported into our pytrees —
    the same import route as the shipped pickle, available everywhere."""
    from sklearn.ensemble import GradientBoostingClassifier, StackingClassifier
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    from machine_learning_replications_tpu.persist import import_stacking

    rng = np.random.default_rng(7)
    n, f = 300, 17
    X = rng.normal(size=(n, f))
    X[:, :10] = (X[:, :10] > 0.3).astype(float)
    y = (X @ rng.normal(size=f) + rng.normal(size=n) > 0.2).astype(float)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = StackingClassifier(
            estimators=[
                ("svc", make_pipeline(
                    StandardScaler(),
                    SVC(class_weight="balanced", probability=True,
                        random_state=2020),
                )),
                ("gbc", GradientBoostingClassifier(
                    n_estimators=20, max_depth=1, random_state=2020)),
                ("lg", LogisticRegression(
                    class_weight="balanced", penalty="l1",
                    solver="liblinear")),
            ],
            final_estimator=LogisticRegression(class_weight="balanced"),
        ).fit(X, y)
    return import_stacking(clf)


@pytest.fixture(scope="module")
def query_rows():
    rng = np.random.default_rng(13)
    X = rng.normal(size=(70, 17))
    X[:, :10] = (X[:, :10] > 0.3).astype(float)
    return X


# ---------------------------------------------------------------------------
# engine: bucket ladder, parity, compile-count bound
# ---------------------------------------------------------------------------


def test_bucket_ladder_selection(stacking_params):
    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8, 64))
    assert [eng.bucket_for(n) for n in (1, 2, 8, 9, 64, 65, 10_000)] == [
        1, 8, 8, 64, 64, 64, 64,
    ]
    with pytest.raises(ValueError):
        BucketedPredictEngine(stacking_params, buckets=())
    with pytest.raises(ValueError):
        BucketedPredictEngine(stacking_params, buckets=(0, 4))
    with pytest.raises(TypeError):
        BucketedPredictEngine(object())


def test_engine_parity_and_padding_neutrality(stacking_params, query_rows):
    """Two layers of the parity contract: (1) pad rows are bit-neutral —
    any two batch sizes landing in the same bucket run the same compiled
    program and agree exactly on shared rows; (2) the bucketed path
    matches the direct eager predict to float tolerance (XLA fusion may
    regroup last-ulp float ops vs op-by-op dispatch)."""
    from machine_learning_replications_tpu.models import stacking

    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8, 64))
    direct = np.asarray(stacking.predict_proba1(stacking_params, query_rows))
    for n in (1, 2, 7, 8, 9, 63, 64, 70):
        got = eng.predict(query_rows[:n])
        assert got.shape == (n,)
        np.testing.assert_allclose(got, direct[:n], rtol=1e-12, atol=1e-15)
    # bit-for-bit padding neutrality within a shared batch plan: 2 and 7
    # rows both run the padded (8,) plan; 40 and 63 both the padded (64,)
    assert eng.plan_batch(2) == eng.plan_batch(7) == (8,)
    assert eng.plan_batch(40) == eng.plan_batch(63) == (64,)
    np.testing.assert_array_equal(
        eng.predict(query_rows[:7])[:2], eng.predict(query_rows[:2])
    )
    np.testing.assert_array_equal(
        eng.predict(query_rows[:63])[:40], eng.predict(query_rows[:40])
    )
    # batch shaping: 9 rows split into a full 8-chunk plus a 1-chunk
    # (zero pad rows) instead of padding 55 rows into the 64 bucket —
    # and the split is exactly those two programs on those rows, so the
    # shaped result is bit-identical to running the chunks by hand
    assert eng.plan_batch(9) == (8, 1)
    np.testing.assert_array_equal(
        eng.predict(query_rows[:9]),
        np.concatenate([
            eng.predict(query_rows[:8]), eng.predict(query_rows[8:9]),
        ]),
    )


def test_engine_compile_count_bound(stacking_params, query_rows):
    """At most ONE XLA compile per ladder bucket, no matter what batch
    sizes traffic presents — the trace counter increments exactly when jit
    traces (once per compile)."""
    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8, 64))
    eng.warmup()
    assert eng.trace_counts == {1: 1, 8: 1, 64: 1}
    for n in (1, 2, 3, 5, 7, 8, 9, 30, 64, 65, 70):
        eng.predict(query_rows[:n])
    # mixed traffic added zero new traces: the cache is bounded and warm
    assert eng.trace_counts == {1: 1, 8: 1, 64: 1}


def test_engine_oversize_batch_chunks(stacking_params, query_rows):
    from machine_learning_replications_tpu.models import stacking

    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8))
    got = eng.predict(query_rows)  # 70 rows through 8-row chunks
    direct = np.asarray(stacking.predict_proba1(stacking_params, query_rows))
    np.testing.assert_allclose(got, direct, rtol=1e-12, atol=1e-15)
    assert set(eng.trace_counts) <= {1, 8}
    assert eng.predict(np.empty((0, 17))).shape == (0,)
    with pytest.raises(ValueError, match="contract rows"):
        eng.predict(np.zeros((3, 5)))


# ---------------------------------------------------------------------------
# batcher: flush policy, admission control, drain
# ---------------------------------------------------------------------------


class _StubEngine:
    """Deterministic engine double: mean of each row, optional delay/block."""

    def __init__(self, delay_s: float = 0.0):
        self.delay_s = delay_s
        self.batches: list[int] = []
        self.release = threading.Event()
        self.release.set()

    def predict(self, X):
        self.release.wait(5.0)
        if self.delay_s:
            time.sleep(self.delay_s)
        self.batches.append(X.shape[0])
        return X.mean(axis=1)

    def bucket_for(self, n):
        b = 1
        while b < n:
            b *= 2
        return b


def test_batcher_flushes_full_batch_immediately():
    eng = _StubEngine()
    m = ServingMetrics()
    b = MicroBatcher(eng, max_batch_size=4, max_wait_ms=10_000, max_queue=64,
                     metrics=m)
    try:
        eng.release.clear()  # hold the engine so one full batch accumulates
        futs = [b.submit(np.full(17, i)) for i in range(4)]
        eng.release.set()
        got = [f.result(timeout=5.0) for f in futs]
        assert got == [float(i) for i in range(4)]
        # a full batch must flush well before the (absurd) 10 s wait bound
        assert 4 in eng.batches
        assert m.requests_total.value == 4
        assert m.batches_total.value >= 1
    finally:
        b.close()


def test_batcher_flush_timeout_single_request():
    eng = _StubEngine()
    b = MicroBatcher(eng, max_batch_size=64, max_wait_ms=30.0, max_queue=64)
    try:
        t0 = time.monotonic()
        fut = b.submit(np.full(17, 2.0))
        assert fut.result(timeout=5.0) == 2.0
        elapsed = time.monotonic() - t0
        # the lone request waited out (roughly) the coalescing window, not
        # the full-batch count — generous upper bound for CI jitter
        assert elapsed < 5.0
        assert eng.batches == [1]
    finally:
        b.close()


def test_batcher_sheds_when_queue_full():
    eng = _StubEngine()
    m = ServingMetrics()
    b = MicroBatcher(eng, max_batch_size=4, max_wait_ms=50.0, max_queue=3,
                     metrics=m)
    try:
        eng.release.clear()  # wedge the engine: the queue can only grow
        futs = []
        shed = 0
        for i in range(12):
            try:
                futs.append(b.submit(np.full(17, i)))
            except Overloaded:
                shed += 1
        assert shed > 0, "a bounded queue must shed under a wedged engine"
        assert m.shed_total.value == shed
        # admitted requests still complete once the engine unwedges
        eng.release.set()
        for f in futs:
            assert isinstance(f.result(timeout=5.0), float)
    finally:
        b.close()


def test_batcher_graceful_drain():
    eng = _StubEngine(delay_s=0.02)
    b = MicroBatcher(eng, max_batch_size=2, max_wait_ms=5_000, max_queue=64)
    futs = [b.submit(np.full(17, i)) for i in range(7)]
    b.close(drain=True)  # stops admission, flushes everything admitted
    assert all(f.done() for f in futs)
    assert [f.result() for f in futs] == [float(i) for i in range(7)]
    with pytest.raises(RuntimeError, match="closed"):
        b.submit(np.full(17, 0.0))


def test_batcher_close_without_drain_fails_pending():
    eng = _StubEngine()
    eng.release.clear()
    b = MicroBatcher(eng, max_batch_size=64, max_wait_ms=5_000, max_queue=64)
    futs = [b.submit(np.full(17, i)) for i in range(3)]
    eng.release.set()
    b.close(drain=False, timeout=5.0)
    for f in futs:
        if not f.done() or f.exception() is not None:
            continue
        # a fast flush may legitimately win the race; values stay correct
        assert isinstance(f.result(), float)


def test_batcher_skips_cancelled_requests():
    """A request cancelled while queued (the server's deadline-expiry
    path) must be dropped at flush time — the engine never computes it —
    while its batchmates still get answers."""
    eng = _StubEngine()
    # 10 s wait bound + batch of 4: nothing flushes until the 4th submit,
    # so the cancel below deterministically lands while f1 is queued.
    b = MicroBatcher(eng, max_batch_size=4, max_wait_ms=10_000, max_queue=64)
    try:
        f0 = b.submit(np.full(17, 0.0))
        f1 = b.submit(np.full(17, 1.0))
        f2 = b.submit(np.full(17, 2.0))
        assert f1.cancel(), "a queued future must be cancellable"
        f3 = b.submit(np.full(17, 3.0))  # fills the batch -> flush
        assert f0.result(timeout=5.0) == 0.0
        assert f2.result(timeout=5.0) == 2.0
        assert f3.result(timeout=5.0) == 3.0
        assert f1.cancelled()
        # only the three live rows reached the engine
        assert sum(eng.batches) == 3
    finally:
        b.close()


def test_batcher_engine_error_propagates():
    class Boom:
        def predict(self, X):
            raise RuntimeError("boom")

    m = ServingMetrics()
    b = MicroBatcher(Boom(), max_batch_size=2, max_wait_ms=1.0, metrics=m)
    try:
        fut = b.submit(np.full(17, 1.0))
        with pytest.raises(RuntimeError, match="boom"):
            fut.result(timeout=5.0)
        assert m.errors_total.value == 1
    finally:
        b.close()


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------


def test_metrics_quantiles_and_render():
    m = ServingMetrics()
    for v in np.linspace(0.001, 0.1, 1000):
        m.latency.observe(float(v))
    p50, p95, p99 = m.latency.quantile((0.5, 0.95, 0.99))
    assert 0.045 < p50 < 0.055
    assert 0.09 < p95 < 0.1
    assert p95 < p99 <= 0.1
    m.requests_total.inc(3)
    m.batch_size.observe(4)
    m.padding_waste.observe(4)
    text = m.render_prometheus()
    assert "serve_requests_total 3" in text
    assert 'serve_request_latency_quantile_seconds{quantile="0.99"}' in text
    assert "serve_batch_size_rows_count 1" in text
    # Exposition validity: every family declares HELP/TYPE exactly once,
    # and no samples for a family appear before its TYPE line (a strict
    # Prometheus scraper rejects the whole page otherwise).
    lines = text.splitlines()
    for fam in (
        "serve_request_latency_seconds",
        "serve_request_latency_quantile_seconds",
    ):
        type_lines = [l for l in lines if l.startswith(f"# TYPE {fam} ")]
        assert len(type_lines) == 1
        first_sample = next(
            i for i, l in enumerate(lines)
            if l.startswith(fam)
        )
        assert lines.index(type_lines[0]) < first_sample
    snap = m.snapshot()
    assert snap["requests_total"] == 3
    assert snap["latency_seconds"]["count"] == 1000


def test_metrics_snapshot_is_strict_json_before_traffic():
    """Empty-window quantiles must serialize as null, not a bare NaN token
    (which json.dumps emits and every strict JSON parser rejects)."""
    snap = ServingMetrics().snapshot()
    assert snap["latency_seconds"]["p50"] is None
    json.loads(json.dumps(snap))  # round-trips under the strict parser


# ---------------------------------------------------------------------------
# HTTP server end-to-end (real sockets, loopback)
# ---------------------------------------------------------------------------


def _post(url, obj, timeout=30.0):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def _get(url, timeout=30.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.read().decode()


@pytest.fixture()
def served(stacking_params):
    handle = make_server(
        stacking_params, port=0, buckets=(1, 8), max_wait_ms=2.0,
        max_queue=32,
    ).start_background()
    host, port = handle.address
    yield handle, f"http://{host}:{port}"
    handle.shutdown()


def test_http_predict_healthz_metrics(served, stacking_params):
    from machine_learning_replications_tpu.models import stacking

    handle, url = served
    status, body = _post(url + "/predict", dict(EXAMPLE_PATIENT))
    assert status == 200
    direct = float(stacking.predict_proba1(stacking_params, patient_row())[0])
    assert body["probability"] == direct  # served == single-patient path
    assert body["text"] == (
        f"Probability of progressive HF is: {100.0 * direct:.2f} %"
    )

    status, body = _get(url + "/healthz")
    health = json.loads(body)
    assert status == 200 and health["status"] == "ok"
    assert health["warm"] is True and health["buckets"] == [1, 8]

    status, text = _get(url + "/metrics")
    assert status == 200
    assert "serve_requests_total" in text
    status, body = _get(url + "/metrics?format=json")
    assert json.loads(body)["requests_total"] >= 1


def test_http_metrics_strict_exposition_with_jax_counters(served):
    """ISSUE 2 acceptance: the /metrics page passes the strict Prometheus
    text-exposition validator, includes the jax compile-count /
    compile-seconds counters from the global registry, and keeps every
    pre-existing serve_* family byte-identical to the standalone
    ServingMetrics render (the registry page is appended after)."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import validate_metrics
    finally:
        _sys.path.pop(0)

    handle, url = served
    _post(url + "/predict", dict(EXAMPLE_PATIENT))  # some traffic
    status, text = _get(url + "/metrics")
    assert status == 200
    assert validate_metrics.validate(text) == [], \
        validate_metrics.validate(text)
    # jax runtime accounting present (make_server installs obs.jaxmon
    # before the engine, so warmup compiles are counted)
    assert "# TYPE jax_compiles_total counter" in text
    assert "# TYPE jax_compile_seconds_total counter" in text
    # serve_* families byte-identical to the standalone ServingMetrics
    # render: the page IS that render (same lines, same order) with the
    # registry appended after. Values can move between two reads, so
    # compare every line with its trailing value token stripped.
    def shape(page):
        return [
            line if line.startswith("#") else line.rsplit(" ", 1)[0]
            for line in page.splitlines()
        ]

    standalone = shape(handle.metrics.render_prometheus())
    assert shape(text)[: len(standalone)] == standalone
    # the global registry's JSON snapshot rides the json format too
    status, body = _get(url + "/metrics?format=json")
    snap = json.loads(body)
    assert "jax_compiles_total" in snap["runtime"]


def test_http_rejects_contract_violations(served):
    _, url = served
    for bad in (
        {"Not_A_Variable": 1},                       # unknown key
        {"Dyspnea": 1},                              # missing 16 variables
        {**EXAMPLE_PATIENT, "Dyspnea": "severe"},    # non-numeric
        # json.loads admits the NaN/Infinity tokens; the contract must not
        {**EXAMPLE_PATIENT, "Ejection_Fraction": float("nan")},
        {**EXAMPLE_PATIENT, "Ejection_Fraction": float("inf")},
        [1, 2, 3],                                   # not an object
    ):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(url + "/predict", bad)
        assert ei.value.code == 400
        ei.value.read()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(url + "/nope")
    assert ei.value.code == 404
    ei.value.read()
    # Oversized body: rejected from the Content-Length header alone (413),
    # never buffered. The server may close the connection before the
    # client finishes streaming, which some stacks surface as a socket
    # error rather than the status line — both prove the cap.
    big = json.dumps({**EXAMPLE_PATIENT, "pad": "x" * (1 << 17)})
    try:
        req = urllib.request.Request(
            url + "/predict", data=big.encode(),
            headers={"Content-Type": "application/json"},
        )
        urllib.request.urlopen(req, timeout=30.0).read()
        raise AssertionError("oversized body must not be accepted")
    except urllib.error.HTTPError as e:
        assert e.code == 413
        e.read()
    except (urllib.error.URLError, ConnectionError):
        pass


def test_http_404_with_body_closes_connection(served):
    """A POST to an unknown path leaves its body unread; the server must
    close the keep-alive connection, or the stale bytes would be parsed as
    the next request line (connection desync)."""
    import socket

    handle, url = served
    host, port = handle.address
    body = json.dumps(dict(EXAMPLE_PATIENT)).encode()
    with socket.create_connection((host, port), timeout=10) as s:
        s.sendall(
            b"POST /predic HTTP/1.1\r\nHost: x\r\n"
            b"Content-Length: %d\r\n\r\n%b" % (len(body), body)
        )
        chunks = []
        while True:  # read to EOF — blocks past the timeout if the
            b = s.recv(65536)  # server wrongly kept the connection open
            if not b:
                break
            chunks.append(b)
        reply = b"".join(chunks)
        assert b"404" in reply.split(b"\r\n", 1)[0]


def test_http_concurrent_requests_batch(served, stacking_params):
    """Concurrent clients coalesce into micro-batches; every reply equals
    the single-row path."""
    from machine_learning_replications_tpu.models import stacking

    handle, url = served
    direct = float(stacking.predict_proba1(stacking_params, patient_row())[0])
    results, errs = [], []

    def one():
        try:
            _, body = _post(url + "/predict", dict(EXAMPLE_PATIENT))
            results.append(body["probability"])
        except Exception as exc:  # pragma: no cover - diagnostic aid
            errs.append(exc)

    threads = [threading.Thread(target=one) for _ in range(24)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert results == [direct] * 24
    assert handle.metrics.batches_total.value >= 1


# ---------------------------------------------------------------------------
# load generator (in-process, against a real served instance)
# ---------------------------------------------------------------------------


def test_loadgen_closed_loop_artifact(served, tmp_path):
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    _, url = served
    out = tmp_path / "SERVE_BENCH_test.json"
    rc = loadgen.main([
        "--url", url, "--mode", "closed", "--concurrency", "4",
        "--duration", "1.0", "--out", str(out),
    ])
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["kind"] == "serve_bench"
    assert art["n_ok"] > 0 and art["n_err"] == 0
    assert art["achieved_qps"] > 0
    for q in ("p50", "p95", "p99"):
        assert art["latency_ms"][q] > 0


def test_loadgen_open_loop_sheds_under_overload(stacking_params, tmp_path):
    """Open-loop overload against a tiny queue and a deliberately slowed
    engine must produce explicit 503 sheds, counted in the artifact and in
    the server's metrics — bounded-queue behavior, measured."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    handle = make_server(
        stacking_params, port=0, buckets=(1, 8), max_wait_ms=1.0,
        max_queue=2,
    ).start_background()
    try:
        # slow every flush down so the offered rate must overrun the queue
        real_predict = handle.engine.predict

        def slow_predict(X):
            time.sleep(0.05)
            return real_predict(X)

        handle.batcher._engine = type(
            "Slow", (), {
                "predict": staticmethod(slow_predict),
                "bucket_for": staticmethod(handle.engine.bucket_for),
            },
        )()
        host, port = handle.address
        out = tmp_path / "SERVE_BENCH_overload.json"
        rc = loadgen.main([
            "--url", f"http://{host}:{port}", "--mode", "open",
            "--qps", "200", "--duration", "1.0", "--out", str(out),
        ])
        assert rc == 0
        art = json.loads(out.read_text())
        assert art["n_shed"] > 0, art
        assert art["shed_rate"] > 0
        assert handle.metrics.shed_total.value == art["n_shed"]
        assert art["n_ok"] > 0  # shedding, not collapse: admitted work completes
    finally:
        handle.shutdown()


# ---------------------------------------------------------------------------
# full-pipeline and shipped-pickle parity with the CLI path
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def pipeline_params():
    """A small but real fit_pipeline model (fast config, synthetic rows)."""
    from machine_learning_replications_tpu.config import ExperimentConfig
    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.models import pipeline

    cfg = ExperimentConfig.from_json(json.dumps({
        "gbdt": {"n_estimators": 5},
        "svc": {"platt_cv": 2, "max_iter": 2000},
        "stacking": {"cv_folds": 2},
        "select": {"cv_folds": 3, "n_alphas": 20},
    }))
    X, y, _ = make_cohort(n=160, seed=2020, missing_rate=0.03)
    params, _ = pipeline.fit_pipeline(X, y, cfg)
    return params


def test_pipeline_engine_matches_cli_route(pipeline_params, query_rows):
    """Served probabilities through a full-pipeline checkpoint equal the
    CLI's predict --model route (pipeline_predict_proba1_contract) for the
    example patient and for varied batched rows."""
    from machine_learning_replications_tpu.models import pipeline

    eng = BucketedPredictEngine(pipeline_params, buckets=(1, 8))
    eng.warmup()
    x = patient_row()
    cli_prob = float(
        pipeline.pipeline_predict_proba1_contract(pipeline_params, x)[0]
    )
    served = eng.predict(x)
    np.testing.assert_array_equal(served, [cli_prob])

    batch = np.asarray(
        pipeline.pipeline_predict_proba1_contract(
            pipeline_params, query_rows[:13]
        )
    )
    np.testing.assert_allclose(
        eng.predict(query_rows[:13]), batch, rtol=1e-12, atol=1e-15
    )
    # compile bound holds on the pipeline route too
    assert eng.trace_counts == {1: 1, 8: 1}

    # dual-path parity on the NaN-imputed route: the host fast path runs
    # the SAME contract_rows_to_x64 → impute_select → stacked-blend
    # composition (non-schema columns NaN, KNN-imputed), bit-for-bit
    # identical to the device path's same-shape program for singles and
    # shared-bucket groups
    from machine_learning_replications_tpu.serve import HostScorer

    host = HostScorer(pipeline_params, buckets=(1, 8))
    host.warmup()
    np.testing.assert_array_equal(host.predict(x), eng.predict(x))
    for i in range(4):
        np.testing.assert_array_equal(
            host.predict(query_rows[i:i + 1]),
            eng.predict(query_rows[i:i + 1]),
        )
    np.testing.assert_array_equal(
        host.predict(query_rows[:5]), eng.predict(query_rows[:5])
    )


# ---------------------------------------------------------------------------
# request-scoped observability over real sockets (ISSUE 3)
# ---------------------------------------------------------------------------


def _post_with_id(url, obj, rid=None, timeout=30.0):
    headers = {"Content-Type": "application/json"}
    if rid is not None:
        headers["X-Request-Id"] = rid
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(), headers=headers
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.headers.get("X-Request-Id"), \
            json.loads(resp.read())


def test_request_id_echo_and_concurrent_uniqueness(served):
    """Every /predict reply carries X-Request-Id: an inbound id is echoed
    verbatim; N parallel POSTs each get a UNIQUE generated id while the
    batcher coalesces them into shared flushes; and every tail-sampled
    trace's phase durations sum to ≤ (and nearly all of) its end-to-end
    latency."""
    handle, url = served
    # inbound id echoed verbatim, Dapper-style propagation
    _, echoed, _ = _post_with_id(
        url + "/predict", dict(EXAMPLE_PATIENT), rid="upstream-7f3a"
    )
    assert echoed == "upstream-7f3a"

    ids, errs = [], []

    def one():
        try:
            _, rid, _ = _post_with_id(url + "/predict", dict(EXAMPLE_PATIENT))
            ids.append(rid)
        except Exception as exc:  # pragma: no cover - diagnostic aid
            errs.append(exc)

    n = 24
    threads = [threading.Thread(target=one) for _ in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert len(ids) == n and len(set(ids)) == n  # unique per request
    assert all(rid and len(rid) == 16 for rid in ids)
    # coalescing really happened: fewer flushes than requests
    assert handle.metrics.batches_total.value < \
        handle.metrics.requests_total.value

    status, body = _get(url + "/debug/requests")
    assert status == 200
    dbg = json.loads(body)
    sampled = dbg["requests"]
    assert sampled, "fresh recorder must have bootstrap samples"
    for tr in sampled:
        total = tr["total_seconds"]
        phase_sum = sum(p["seconds"] for p in tr["phases"].values())
        assert phase_sum <= total + 1e-6, tr
        if tr["status"] == "ok":
            # the five phases attribute (nearly) the whole request
            assert set(tr["phases"]) == {
                "parse", "queue_wait", "batch_assembly",
                "device_compute", "respond",
            }
            assert phase_sum >= 0.95 * total, tr
            assert tr["bucket"] in (1, 8)
    # the traced requests ARE the admitted ones (join by id works): every
    # sample on this fresh-fixture server came from a request this test
    # sent, under the id the server echoed back
    sampled_ids = {tr["request_id"] for tr in sampled}
    assert sampled_ids and sampled_ids <= set(ids) | {"upstream-7f3a"}


def test_healthz_carries_load_signal(served):
    handle, url = served
    _, body = _get(url + "/healthz")
    health = json.loads(body)
    assert health["queue_depth"] == 0
    assert health["uptime_seconds"] >= 0
    assert health["run_id"] is None  # no journal active

    from machine_learning_replications_tpu.obs import journal

    jrn = journal.RunJournal("/tmp/_serve_hz_j.jsonl", command="serve")
    journal.set_journal(jrn)
    try:
        _, body = _get(url + "/healthz")
        assert json.loads(body)["run_id"] == jrn.manifest["run_id"]
    finally:
        journal.set_journal(None)
        jrn.close()


def test_debug_requests_keeps_failures(served):
    """Tail sampling never drops failures: a 400 (contract violation)
    shows up in /debug/requests with its echoed request id."""
    _, url = served
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post_with_id(url + "/predict", {"Dyspnea": 1}, rid="bad-req-1")
    assert ei.value.code == 400
    assert ei.value.headers.get("X-Request-Id") == "bad-req-1"
    ei.value.read()
    status, body = _get(url + "/debug/requests?n=200")
    dbg = json.loads(body)
    bad = [t for t in dbg["requests"] if t["request_id"] == "bad-req-1"]
    assert bad and bad[0]["status"] == "bad_request"
    assert bad[0]["sampled_reason"] == "failure"
    # stats + SLO snapshot ride along
    assert dbg["stats"]["kept_total"] >= 1
    assert {s["name"] for s in dbg["slo"]} == {
        "latency_le_250ms", "availability",
    }
    with pytest.raises(urllib.error.HTTPError) as ei:
        _get(url + "/debug/requests?n=nope")
    assert ei.value.code == 400
    ei.value.read()


def test_debug_profile_single_flight_http(served):
    """ISSUE 3 acceptance (c): concurrent /debug/profile calls produce a
    non-empty artifact exactly once; the losers get an immediate 409."""
    _, url = served
    results = []

    def one():
        try:
            status, body = _get(url + "/debug/profile?seconds=0.4")
            results.append((status, json.loads(body)))
        except urllib.error.HTTPError as exc:
            results.append((exc.code, json.loads(exc.read())))

    threads = [threading.Thread(target=one) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    codes = sorted(code for code, _ in results)
    assert codes == [200, 409, 409], results
    artifact = next(body for code, body in results if code == 200)
    assert artifact["total_bytes"] > 0 and artifact["files"]
    assert os.path.isdir(artifact["profile_dir"])
    busy = next(body for code, body in results if code == 409)
    assert "in flight" in busy["error"]
    # bad inputs are 400, not capture attempts
    for q in ("seconds=abc", "seconds=0", "seconds=1e9"):
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(url + f"/debug/profile?{q}")
        assert ei.value.code == 400
        ei.value.read()


def test_metrics_gains_queue_wait_and_slo_families(served):
    """The new families ride the same strict-validated /metrics page:
    serve_queue_wait_seconds (tail queueing without a trace), slo_* burn
    gauges, and the flight recorder's sampling counters."""
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import validate_metrics
    finally:
        _sys.path.pop(0)

    _, url = served
    _post(url + "/predict", dict(EXAMPLE_PATIENT))
    status, text = _get(url + "/metrics")
    assert validate_metrics.validate(text) == [], validate_metrics.validate(text)
    assert "# TYPE serve_queue_wait_seconds histogram" in text
    assert "serve_queue_wait_seconds_count" in text
    assert '# TYPE slo_burn_rate gauge' in text
    assert 'slo_error_budget_remaining_ratio{slo="availability"}' in text
    assert "# TYPE reqtrace_sampled_total counter" in text
    # queue-wait got observed for the flushed request
    qw_count = next(
        line for line in text.splitlines()
        if line.startswith("serve_queue_wait_seconds_count")
    )
    assert float(qw_count.split()[-1]) >= 1
    # the JSON snapshot carries the histogram too
    _, body = _get(url + "/metrics?format=json")
    snap = json.loads(body)
    assert snap["queue_wait_seconds"]["count"] >= 1


def test_sampled_requests_merge_under_flush_spans(stacking_params):
    """ISSUE 3 acceptance (b): with an active tracer, sampled request
    traces merge into the Chrome-trace export — request/phase events on
    per-request lanes, and a req:<id> slice positionally CONTAINED in its
    flush span (same tid, inside the flush interval), which is exactly
    what Perfetto renders as request-nested-under-flush."""
    from machine_learning_replications_tpu.obs import spans

    tracer = spans.Tracer("test-serve-trace")
    spans.set_tracer(tracer)
    try:
        handle = make_server(
            stacking_params, port=0, buckets=(1, 8), max_wait_ms=2.0,
        ).start_background()
        try:
            host, port = handle.address
            url = f"http://{host}:{port}"
            threads = [
                threading.Thread(
                    target=_post, args=(url + "/predict", dict(EXAMPLE_PATIENT))
                )
                for _ in range(12)
            ]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        finally:
            handle.shutdown()
    finally:
        spans.set_tracer(None)
    export = tracer.export()
    evs = [e for e in export["traceEvents"] if e.get("ph") == "X"]
    flushes = [e for e in evs if e["name"] == "serve:flush"]
    req_slices = [e for e in evs if e["name"].startswith("req:")]
    lanes = [e for e in evs if e["name"].startswith("request ")]
    assert flushes and req_slices and lanes
    # flush spans now carry their correlation annotations
    assert all("flush_seq" in f["args"] for f in flushes)
    assert all(f["args"]["cold_compile"] in (True, False) for f in flushes)
    for c in req_slices:
        assert any(
            f["tid"] == c["tid"]
            and f["ts"] - 1 <= c["ts"]
            and c["ts"] + c["dur"] <= f["ts"] + f["dur"] + 1
            for f in flushes
        ), f"req slice {c} not contained in any flush span"
    # lane events: each sampled request's phases are contained in its
    # request span on the same lane tid
    for lane_ev in lanes:
        rid = lane_ev["args"]["request_id"]
        phases = [
            e for e in evs
            if e["tid"] == lane_ev["tid"]
            and e["args"].get("request_id") == rid
            and e["name"] in (
                "parse", "queue_wait", "batch_assembly",
                "device_compute", "respond",
            )
        ]
        assert phases, f"no phase events for sampled request {rid}"
        for p in phases:
            assert lane_ev["ts"] - 1 <= p["ts"]
            assert p["ts"] + p["dur"] <= lane_ev["ts"] + lane_ev["dur"] + 1


def test_timeout_trace_sampled_with_partition_intact(stacking_params):
    """A 504'd request is always sampled (failure), and freezing the
    trace before the reply keeps the partition invariant even when the
    flush thread races the cancel: phases never sum past the total."""
    handle = make_server(
        stacking_params, port=0, buckets=(1, 8), max_wait_ms=1.0,
        request_timeout_s=0.15,
    ).start_background()
    try:
        real_predict = handle.engine.predict

        def slow_predict(X):
            time.sleep(0.4)  # past the 0.15 s request deadline
            return real_predict(X)

        handle.batcher._engine = type(
            "Slow", (), {
                "predict": staticmethod(slow_predict),
                "bucket_for": staticmethod(handle.engine.bucket_for),
            },
        )()
        host, port = handle.address
        url = f"http://{host}:{port}"
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_with_id(url + "/predict", dict(EXAMPLE_PATIENT),
                          rid="will-timeout")
        assert ei.value.code == 504
        assert ei.value.headers.get("X-Request-Id") == "will-timeout"
        ei.value.read()
        assert handle.metrics.timeouts_total.value == 1
        _, body = _get(url + "/debug/requests?n=50")
        sample = next(
            t for t in json.loads(body)["requests"]
            if t["request_id"] == "will-timeout"
        )
        assert sample["status"] == "timeout"
        assert sample["sampled_reason"] == "failure"
        total = sample["total_seconds"]
        assert total >= 0.15  # the deadline wait is in the total
        for p in sample["phases"].values():
            assert p["offset_seconds"] + p["seconds"] <= total + 1e-6
        assert sum(
            p["seconds"] for p in sample["phases"].values()
        ) <= total + 1e-6
    finally:
        handle.shutdown()


def test_cold_compile_attributed_on_trace(stacking_params):
    """A flush that pays a bucket compile is flagged: serve without
    warmup, and the first request's sampled trace (bootstrap keeps it)
    carries cold_compile=True; a later same-bucket request is warm."""
    handle = make_server(
        stacking_params, port=0, buckets=(1,), max_wait_ms=1.0,
        warmup=False,
    ).start_background()
    try:
        host, port = handle.address
        url = f"http://{host}:{port}"
        _post(url + "/predict", dict(EXAMPLE_PATIENT))
        _post(url + "/predict", dict(EXAMPLE_PATIENT))
        _, body = _get(url + "/debug/requests")
        samples = json.loads(body)["requests"]
        assert len(samples) == 2
        # newest first: the second request hit the warm executable
        assert samples[0]["cold_compile"] is False
        assert samples[1]["cold_compile"] is True
    finally:
        handle.shutdown()


def test_loadgen_records_worst_request_ids(served, tmp_path):
    """Satellite: the loadgen artifact carries the server-echoed ids of
    its worst-latency requests — the join keys for /debug/requests."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    _, url = served
    out = tmp_path / "SERVE_BENCH_ids.json"
    rc = loadgen.main([
        "--url", url, "--mode", "closed", "--concurrency", "3",
        "--duration", "1.0", "--out", str(out),
    ])
    assert rc == 0
    art = json.loads(out.read_text())
    worst = art["worst_requests"]
    assert 0 < len(worst) <= 10
    assert worst == sorted(worst, key=lambda w: -w["latency_ms"])
    for w in worst:
        assert w["status"] == "ok"
        assert w["request_id"] and len(w["request_id"]) == 16
        assert w["latency_ms"] > 0
    # the join target exists: at least one worst id may be sampled; the
    # FORMAT contract (ids comparable to trace request_ids) always holds
    _, body = _get(url + "/debug/requests?n=500")
    sampled_ids = {
        t["request_id"] for t in json.loads(body)["requests"]
    }
    assert all(isinstance(rid, str) for rid in sampled_ids)


def test_obs_report_joins_all_sources(served, tmp_path):
    """tools/obs_report.py: one report from a live scrape + loadgen
    artifact + journal, with the client-vs-server join section."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import loadgen
        import obs_report
    finally:
        sys.path.pop(0)

    from machine_learning_replications_tpu.obs import journal

    handle, url = served
    jrn = journal.RunJournal(tmp_path / "serve.jsonl", command="serve")
    journal.set_journal(jrn)
    try:
        bench = tmp_path / "SB.json"
        assert loadgen.main([
            "--url", url, "--mode", "closed", "--concurrency", "3",
            "--duration", "1.0", "--out", str(bench),
        ]) == 0
        report_path = tmp_path / "REPORT.md"
        assert obs_report.main([
            "--url", url, "--bench", str(bench),
            "--journal", str(jrn.path), "--out", str(report_path),
        ]) == 0
    finally:
        journal.set_journal(None)
        jrn.close()
    report = report_path.read_text()
    for section in (
        "# Observability report", "## Run", "## Traffic",
        "## Runtime (XLA accounting)", "## SLO", "## Model quality",
        "## Tail-sampled requests", "## Journal digest",
        "## Bench join",
    ):
        assert section in report, f"missing section {section!r}"
    assert jrn.manifest["run_id"] in report
    assert "latency_le_250ms" in report
    assert "flushes" in report  # journal digest saw the batcher events


@pytest.mark.skipif(not _HAVE_REFERENCE_PKL, reason="reference pkl absent")
def test_shipped_pickle_served_equals_cli(capsys):
    """The acceptance example: the shipped reference pickle served through
    the engine prints the same 'Probability of progressive HF is: 27.09 %'
    contract line as `cli.py predict` — bit-for-bit equal probability."""
    from machine_learning_replications_tpu import cli
    from machine_learning_replications_tpu.persist import (
        load_inference_params,
    )
    from machine_learning_replications_tpu.serve.server import OUTPUT_CONTRACT

    assert cli.main(["predict"]) == 0
    cli_line = capsys.readouterr().out.strip()

    params = load_inference_params()
    eng = BucketedPredictEngine(params, buckets=(1, 8))
    prob = float(eng.predict(patient_row())[0])
    assert OUTPUT_CONTRACT.format(100.0 * prob) == cli_line
    assert "27.09" in cli_line  # SURVEY.md §2.3 pinned example output


# ---------------------------------------------------------------------------
# model-quality monitoring (obs.quality) through the serving stack
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def quality_cohort(stacking_params):
    """The 17-column cohort the module's sklearn fixture trained on, plus
    a matching reference profile — training rows scored through the
    SERVED ensemble, exactly what ``fit_pipeline`` records, so the score
    distribution baseline matches what serving will produce."""
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.obs import quality

    rng = np.random.default_rng(7)
    n, f = 300, 17
    X = rng.normal(size=(n, f))
    X[:, :10] = (X[:, :10] > 0.3).astype(float)
    y = (X @ rng.normal(size=f) + rng.normal(size=n) > 0.2).astype(float)
    scores = np.asarray(stacking.predict_proba1(stacking_params, X))
    profile = quality.build_reference_profile(X, scores, y)
    return X, profile


def _patient_of(row):
    from machine_learning_replications_tpu.data.schema import SELECTED_17

    return {k: float(v) for k, v in zip(SELECTED_17, row)}


def test_engine_feeds_quality_only_real_rows(stacking_params, quality_cohort):
    """The engine's quality feed: warmup rows never touch the monitor, pad
    rows are sliced off before it, chunked oversize batches count once per
    real row, and member outputs flow through for disagreement."""
    from machine_learning_replications_tpu.obs import quality
    from machine_learning_replications_tpu.obs.registry import MetricsRegistry

    X, profile = quality_cohort
    mon = quality.QualityMonitor(
        profile, registry=MetricsRegistry(), min_rows=10, window=256
    )
    eng = BucketedPredictEngine(
        stacking_params, buckets=(1, 8), quality=mon
    )
    eng.warmup()
    assert mon.snapshot()["rows_total"] == 0  # warmup bypasses the window
    eng.predict(X[:3])  # pads to bucket 8; only 3 real rows may count
    assert mon.snapshot()["rows_total"] == 3
    eng.predict(X[:20])  # beyond the top bucket: chunked, still 20 rows
    snap = mon.snapshot()
    assert snap["rows_total"] == 23
    assert snap["member_disagreement"] is not None  # members flowed through


def test_quality_disabled_without_profile(served):
    """A served bare ensemble with no profile attached: /healthz says
    disabled, /debug/quality explains itself, and both stay strict JSON."""
    _, url = served
    _, body = _get(url + "/healthz")
    assert json.loads(body)["quality"] == {"status": "disabled"}
    _, body = _get(url + "/debug/quality")
    q = json.loads(body)
    assert q["enabled"] is False and "reason" in q


def test_served_quality_ok_then_alert_on_perturbed_traffic(
    stacking_params, quality_cohort, tmp_path
):
    """The E2E drift loop: cohort-distributed traffic keeps status ok;
    perturbing two variables flips it to alert with those variables as
    the top PSI offenders, the transition journaled, /healthz carrying
    the compact block, and the quality_* families validator-clean on
    /metrics."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import validate_metrics
    finally:
        sys.path.pop(0)

    from machine_learning_replications_tpu.obs import journal

    X, profile = quality_cohort
    jrn = journal.RunJournal(tmp_path / "quality.jsonl", command="serve")
    journal.set_journal(jrn)
    handle = make_server(
        stacking_params, port=0, buckets=(1, 8), max_wait_ms=2.0,
        quality_profile=profile, quality_window=512,
    ).start_background()
    try:
        host, port = handle.address
        url = f"http://{host}:{port}"
        for i in range(240):
            status, _ = _post(url + "/predict", _patient_of(X[i % len(X)]))
            assert status == 200
        _, body = _get(url + "/debug/quality")
        q = json.loads(body)
        assert q["enabled"] is True and q["status"] == "ok"
        assert q["rows_total"] == 240
        assert q["score_psi"] is not None
        _, body = _get(url + "/healthz")
        assert json.loads(body)["quality"]["status"] == "ok"

        # upstream unit bug: wall thickness 10x, EF halved
        for i in range(240):
            p = _patient_of(X[i % len(X)])
            p["Max_Wall_Thick"] *= 10.0
            p["Ejection_Fraction"] *= 0.5
            status, _ = _post(url + "/predict", p)
            assert status == 200
        _, body = _get(url + "/debug/quality")
        q = json.loads(body)
        assert q["status"] == "alert"
        top2 = {f["name"] for f in q["features"][:2]}
        assert top2 == {"Max_Wall_Thick", "Ejection_Fraction"}
        _, body = _get(url + "/healthz")
        hq = json.loads(body)["quality"]
        assert hq["status"] == "alert"
        assert hq["worst_feature"] in top2
        assert hq["worst_psi"] >= 0.25

        _, page = _get(url + "/metrics")
        assert "quality_feature_psi" in page
        assert "quality_status_transitions_total" in page
        assert validate_metrics.validate(page) == []
    finally:
        handle.shutdown()
        journal.set_journal(None)
        jrn.close()
    events = [json.loads(line) for line in open(tmp_path / "quality.jsonl")]
    trans = [e for e in events if e.get("kind") == "quality_status"]
    assert trans and trans[0]["from_status"] == "ok"
    assert trans[-1]["to_status"] == "alert"


def test_no_quality_flag_disables_even_with_profile(
    stacking_params, quality_cohort
):
    _, profile = quality_cohort
    handle = make_server(
        stacking_params, port=0, buckets=(1,), max_wait_ms=1.0,
        quality_profile=profile, no_quality=True, warmup=False,
    ).start_background()
    try:
        host, port = handle.address
        _, body = _get(f"http://{host}:{port}/debug/quality")
        assert json.loads(body)["enabled"] is False
        assert handle.engine.quality is None
    finally:
        handle.shutdown()


def test_loadgen_perturb_spec_and_onset(served, quality_cohort, tmp_path):
    """Satellite: loadgen --perturb shifts the named variables from the
    --perturb-at point on and records spec + onset in the artifact."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    # unit-level: spec parsing and application
    ops = loadgen.parse_perturb(
        "Ejection_Fraction*0.6,Max_Wall_Thick+8,NYHA_Class=3,Gender-1"
    )
    assert ops == [
        ("Ejection_Fraction", "*", 0.6), ("Max_Wall_Thick", "+", 8.0),
        ("NYHA_Class", "=", 3.0), ("Gender", "-", 1.0),
    ]
    p = loadgen.apply_perturb(dict(EXAMPLE_PATIENT), ops)
    assert p["Ejection_Fraction"] == 55 * 0.6
    assert p["Max_Wall_Thick"] == 13 + 8
    assert p["NYHA_Class"] == 3.0 and p["Gender"] == 0.0
    with pytest.raises(ValueError, match="bad perturb term"):
        loadgen.parse_perturb("Ejection_Fraction~2")

    # end-to-end: a perturbed closed loop against the live server, fed a
    # JSONL cohort, records where the distribution moved
    X, _ = quality_cohort
    patients = tmp_path / "patients.jsonl"
    with open(patients, "w") as f:
        for row in X[:50]:
            f.write(json.dumps(_patient_of(row)) + "\n")
    _, url = served
    out = tmp_path / "SERVE_BENCH_perturb.json"
    rc = loadgen.main([
        "--url", url, "--mode", "closed", "--concurrency", "2",
        "--duration", "1.0", "--patients", str(patients),
        "--perturb", "Ejection_Fraction*0.5", "--perturb-at", "0.5",
        "--out", str(out),
    ])
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["n_ok"] > 0 and art["n_err"] == 0
    assert art["patients"] == str(patients) and art["n_patients"] == 50
    perturb = art["perturb"]
    assert perturb["spec"] == "Ejection_Fraction*0.5"
    assert perturb["at_fraction"] == 0.5
    assert perturb["onset_index"] is not None
    assert 0 < perturb["onset_index"] < art["n_sent"]
    assert perturb["onset_time_s"] >= 0.5


def test_pipeline_served_quality_names_follow_support_mask(pipeline_params):
    """A full-pipeline checkpoint profiles its OWN lasso-selected columns
    (ascending schema order), not the contract order: the served monitor
    must pick the profile up from params.quality automatically and label
    features with the selected schema variable names, or every
    quality_feature_psi series points at the wrong variable."""
    from machine_learning_replications_tpu.data.schema import variable_names

    assert pipeline_params.quality is not None  # fit_pipeline recorded it
    handle = make_server(
        pipeline_params, port=0, buckets=(1,), warmup=False,
    ).start_background()
    try:
        mask = np.asarray(pipeline_params.support_mask)
        expected = [variable_names()[i] for i in np.where(mask)[0]]
        assert list(handle.quality.feature_names) == expected
        host, port = handle.address
        _, body = _get(f"http://{host}:{port}/debug/quality")
        q = json.loads(body)
        assert q["enabled"] is True
        assert [f["name"] for f in q["features"]] == sorted(
            expected, key=expected.index
        )  # below min_rows every psi is None, so profile order is kept
    finally:
        handle.shutdown()


def test_quality_feed_failure_quarantined_not_fatal(
    stacking_params, quality_cohort, tmp_path
):
    """Telemetry must never take serving down: a monitor that raises on
    observe (here: NaN rows from a direct predict() caller — the HTTP
    path rejects them, but the engine API allows them) is quarantined
    with a journaled event, and the prediction still succeeds."""
    from machine_learning_replications_tpu.obs import journal, quality
    from machine_learning_replications_tpu.obs.registry import MetricsRegistry

    X, profile = quality_cohort
    mon = quality.QualityMonitor(
        profile, registry=MetricsRegistry(), min_rows=10, window=64
    )
    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8), quality=mon)
    jrn = journal.RunJournal(tmp_path / "feed.jsonl", command="serve")
    journal.set_journal(jrn)
    try:
        bad = X[:3].copy()
        bad[0, 0] = np.nan
        # the bare route propagates NaN in → NaN out (only the pipeline
        # route imputes); the point is the CALL succeeds and batchmates
        # still get finite answers
        probs = eng.predict(bad)
        assert probs.shape == (3,) and np.isfinite(probs[1:]).all()
        assert eng.quality is None  # feed quarantined, not fatal
        # the quarantine is VISIBLE on every surface still holding the
        # monitor (ServerHandle keeps its reference for /healthz and
        # /debug/quality): frozen stats must not present as live 'ok'
        assert mon.health()["status"] == "disabled"
        snap = mon.snapshot()
        assert snap["enabled"] is False and "quarantined" in snap["reason"]
        eng.predict(X[:3])  # serving continues unobserved
    finally:
        journal.set_journal(None)
        jrn.close()
    events = [json.loads(line) for line in open(tmp_path / "feed.jsonl")]
    disabled = [
        e for e in events if e.get("kind") == "quality_feed_disabled"
    ]
    assert len(disabled) == 1 and "finite" in disabled[0]["error"]


def test_warmup_failure_releases_port_for_immediate_rebind(stacking_params):
    """Satellite (listener lifecycle): a make_server whose warmup fails
    must release the bound port on the way out — the next bind of the
    SAME port (e.g. a supervised worker replacement) succeeds instead of
    EADDRINUSE. Holds per worker in multi-worker mode by construction
    (each worker runs this exact path)."""
    import socket as socketmod

    from machine_learning_replications_tpu.resilience import faults

    s = socketmod.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()

    faults.arm("engine.warmup:raise@once")
    try:
        with pytest.raises(faults.InjectedFault):
            make_server(stacking_params, port=port, buckets=(1,))
    finally:
        faults.reset()
    # the port is free NOW — a fresh server binds it without retry
    handle = make_server(
        stacking_params, port=port, buckets=(1,), warmup=False,
    )
    try:
        assert handle.address[1] == port
    finally:
        handle.shutdown()


def test_loadgen_connections_keepalive_artifact(served, tmp_path):
    """Satellite: --connections N drives the single-threaded event-loop
    client over N persistent keep-alive connections and records reuse
    stats — connections opened ≈ N (sockets really persisted) and many
    requests per connection."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import loadgen
    finally:
        sys.path.pop(0)

    _, url = served
    out = tmp_path / "SERVE_BENCH_conns.json"
    rc = loadgen.main([
        "--url", url, "--connections", "16", "--duration", "1.5",
        "--out", str(out),
    ])
    assert rc == 0
    art = json.loads(out.read_text())
    assert art["n_ok"] > 0 and art["n_err"] == 0
    conns = art["connections"]
    assert conns["client"] == "event-loop"
    assert conns["n_connections"] == 16
    # persistent connections persisted: no reconnect churn, several
    # requests rode each socket
    assert conns["opened_total"] == 16
    assert conns["reconnects"] == 0
    assert conns["requests_total"] == art["n_sent"]
    assert conns["requests_per_connection_mean"] > 1
    # thread-mode closed loop records the block too
    out2 = tmp_path / "SERVE_BENCH_threads.json"
    assert loadgen.main([
        "--url", url, "--mode", "closed", "--concurrency", "2",
        "--duration", "1.0", "--out", str(out2),
    ]) == 0
    art2 = json.loads(out2.read_text())
    assert art2["connections"]["n_connections"] == 2
    assert art2["connections"]["requests_per_connection_mean"] > 1


def test_worker_identity_on_healthz_and_metrics(stacking_params):
    """Multi-worker attribution: a worker-id-carrying server reports the
    id on /healthz and exports serve_worker_info{worker=...} so scrapes
    through the shared SO_REUSEPORT port stay attributable."""
    handle = make_server(
        stacking_params, port=0, buckets=(1,), warmup=False,
        reuse_port=True, worker_id=3,
    ).start_background()
    try:
        host, port = handle.address
        _, body = _get(f"http://{host}:{port}/healthz")
        assert json.loads(body)["worker"] == 3
        _, page = _get(f"http://{host}:{port}/metrics")
        assert 'serve_worker_info{worker="3"} 1' in page
    finally:
        handle.shutdown()


def test_make_server_rejects_mismatched_profile_width(stacking_params):
    """A profile built over the wrong space (e.g. pre-selection 64-column
    rows attached to a bare 17-column ensemble) must fail at startup, not
    on the first served flush."""
    from machine_learning_replications_tpu.obs import quality

    from machine_learning_replications_tpu.obs.registry import REGISTRY

    rng = np.random.default_rng(11)
    X64 = rng.normal(size=(100, 64))
    wide = quality.build_reference_profile(X64, np.full(100, 0.5))
    with pytest.raises(ValueError, match="features wide"):
        make_server(
            stacking_params, port=0, buckets=(1,), warmup=False,
            quality_profile=wide,
        )
    # the rejection happened BEFORE any monitor existed: no phantom
    # 64-wide series (f17..f63 fallback names) leaked into the
    # process-global registry that /metrics renders forever
    fams = {f.name: f for f in REGISTRY.families()}
    fam = fams.get("quality_feature_psi")
    if fam is not None:
        assert all(
            "f63" not in label_values
            for label_values, _ in fam.collect()
        )
