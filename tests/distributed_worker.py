"""Worker process for the two-process ``jax.distributed`` smoke test.

Run as: ``python distributed_worker.py <coordinator> <num_procs> <proc_id>``.
Each worker forces 2 virtual CPU devices, joins the coordination service
through ``parallel.distributed.initialize_distributed`` (the code path
under test — VERDICT r3 missing #3: it had never executed multi-process
anywhere), builds the global mesh, and runs one cross-process psum over a
row-sharded distributed array. Prints ``SMOKE_OK <total> <procs> <devs>``
on success.

It then runs a TRAINING fit across the process boundary (VERDICT r4
missing #3 — bring-up plus one psum proves the channel, not the trainers):
``parallel.fit_gbdt_sharded`` over the 2-process × 2-device global mesh on
a small cohort, asserted stage-by-stage against the single-device
``models.gbdt.fit`` of the same cohort computed locally. Prints
``FIT_OK <n_stages> <deviance>`` on success; any assertion or connection
failure exits non-zero.
"""

import functools
import os
import sys


def main() -> None:
    sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    addr, nprocs, pid = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    flags = os.environ.get("XLA_FLAGS", "")
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"

    from machine_learning_replications_tpu.parallel import distributed
    from machine_learning_replications_tpu.parallel.mesh import DATA_AXIS

    assert distributed.initialize_distributed(addr, nprocs, pid) is True

    import numpy as np
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    idx, count = distributed.process_info()
    assert (idx, count) == (pid, nprocs), (idx, count)
    n_dev = len(jax.devices())
    assert n_dev == 2 * nprocs, n_dev  # global view spans both processes

    mesh = distributed.global_mesh()  # all 4 devices on 'data'
    sharding = NamedSharding(mesh, P(DATA_AXIS))
    host_rows = np.arange(1.0, float(n_dev) + 1.0, dtype=np.float32)
    x = jax.make_array_from_callback(
        (n_dev,), sharding, lambda i: host_rows[i]
    )

    def local_sum(xl):
        return jax.lax.psum(jnp.sum(xl), DATA_AXIS)

    total = jax.jit(
        functools.partial(
            jax.shard_map,
            mesh=mesh, in_specs=P(DATA_AXIS), out_specs=P(), check_vma=False,
        )(local_sum)
    )(x)
    expect = float(host_rows.sum())
    got = float(total)
    assert got == expect, (got, expect)
    print(f"SMOKE_OK {got} {count} {n_dev}", flush=True)

    # --- cross-process sharded TRAINING fit (VERDICT r4 missing #3) -----
    # Every process holds the identical host cohort (deterministic seed);
    # shard_rows/device_put lays global rows over all 4 devices, so each
    # boosting stage's histogram partials psum across the process boundary.
    # The reference fit runs single-device locally in each process.
    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import gbdt
    from machine_learning_replications_tpu.parallel import fit_gbdt_sharded

    X, y, _ = make_cohort(n=96, seed=3)
    Xs = X[:, selected_indices()]
    cfg = GBDTConfig(n_estimators=3, max_depth=1)
    sharded, aux_sh = fit_gbdt_sharded(mesh, Xs, y, cfg)
    single, aux_sd = gbdt.fit(Xs, y, cfg)
    np.testing.assert_array_equal(
        np.asarray(sharded.feature), np.asarray(single.feature)
    )
    np.testing.assert_allclose(
        np.asarray(sharded.value), np.asarray(single.value),
        rtol=1e-5, atol=1e-6,
    )
    np.testing.assert_allclose(
        np.asarray(aux_sh["train_deviance"]),
        np.asarray(aux_sd["train_deviance"]), rtol=1e-5,
    )
    dev_final = float(np.asarray(aux_sh["train_deviance"])[-1])
    print(f"FIT_OK {cfg.n_estimators} {dev_final:.6f}", flush=True)


if __name__ == "__main__":
    main()
