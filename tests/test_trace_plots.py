"""Tracing/profiling utilities and host-side plotting (SURVEY.md §5)."""

import os

import jax.numpy as jnp
import numpy as np
import pytest

from machine_learning_replications_tpu.utils import plots, trace


def test_phase_timer_accumulates():
    t = trace.PhaseTimer()
    with t.phase("a"):
        pass
    with t.phase("a"):
        pass
    with t.phase("b") as ph:
        out = ph.block(jnp.ones(4) * 2)  # blocked on at phase exit
    assert float(out.sum()) == 8.0
    assert t.counts == {"a": 2, "b": 1}
    assert all(s >= 0 for s in t.seconds.values())
    rep = t.report()
    assert "a" in rep and "b" in rep and "total" in rep


def test_nan_guard_raises():
    with pytest.raises(FloatingPointError):
        with trace.nan_guard():
            jnp.log(jnp.zeros(2) - 1.0).block_until_ready()
    # config restored
    import jax

    assert not jax.config.jax_debug_nans


def test_nan_guard_restores_on_body_raise():
    """jax_debug_nans must be restored to its PRIOR value when the body
    raises any exception — including when the guard was entered with the
    flag already on (a nested guard must not clobber the outer scope)."""
    import jax

    assert not jax.config.jax_debug_nans  # test precondition
    with pytest.raises(ValueError, match="mid-scope"):
        with trace.nan_guard():
            raise ValueError("mid-scope")
    assert not jax.config.jax_debug_nans

    # prior-True case: the outer scope's setting survives an inner raise
    jax.config.update("jax_debug_nans", True)
    try:
        with pytest.raises(ValueError):
            with trace.nan_guard():
                raise ValueError("inner")
        assert jax.config.jax_debug_nans
    finally:
        jax.config.update("jax_debug_nans", False)


def test_nan_guard_disabled_is_inert():
    import jax

    with trace.nan_guard(enable=False):
        assert not jax.config.jax_debug_nans
        # NaN production must NOT raise inside a disabled guard
        bad = jnp.log(jnp.zeros(2) - 1.0)
        assert np.isnan(np.asarray(bad)).all()


def test_stage_say_iso8601_utc_and_hoisted_imports(capsys, monkeypatch):
    """stage_say stamps ISO-8601 UTC (multi-hour logs unambiguous across
    midnight/timezones) and honors the MLR_TPU_PROGRESS=0 opt-out; the
    os/sys imports are module-level now (no per-call import)."""
    import re

    trace.stage_say("hello stage")
    err = capsys.readouterr().err
    assert re.match(
        r"^\[pipeline \d{4}-\d{2}-\d{2}T\d{2}:\d{2}:\d{2}Z\] hello stage\n$",
        err,
    )
    # no per-call re-import: os/sys are module globals now, and the
    # function body contains no import statement
    import dis

    assert "os" in vars(trace) and "sys" in vars(trace)
    assert not any(
        ins.opname == "IMPORT_NAME"
        for ins in dis.get_instructions(trace.stage_say)
    )

    monkeypatch.setenv("MLR_TPU_PROGRESS", "0")
    trace.stage_say("suppressed")
    assert capsys.readouterr().err == ""


def test_device_trace_writes(tmp_path):
    with trace.device_trace(str(tmp_path)):
        jnp.ones(8).sum().block_until_ready()
    # profiler emits a plugins/profile/<ts>/ tree
    found = [p for p, _, fs in os.walk(tmp_path) for f in fs]
    assert found, "no trace output written"


def test_roc_pr_figures(tmp_path, rng):
    y = (rng.uniform(size=200) < 0.3).astype(np.float64)
    s = np.clip(y * 0.6 + rng.normal(scale=0.3, size=200), 0, 1)
    roc_p = tmp_path / "roc.png"
    pr_p = tmp_path / "pr.png"
    plots.roc_figure(y, s, out_path=roc_p)
    plots.pr_figure(y, s, out_path=pr_p)
    assert roc_p.stat().st_size > 1000
    assert pr_p.stat().st_size > 1000
