"""Fleet telemetry plane (obs.fleettrace / obs.fleetmetrics + router
wiring): clock-offset estimation, cross-process trace join, exposition
merge math, scrape staleness, and the router's /fleet/metrics +
/fleet/trace + /debug/requests?id= endpoints.

Merge math and the join run against synthetic pages/snapshots (goldens —
the semantics are arithmetic, not I/O); the endpoint tests run the real
router over stub replicas on the real transport, the same pattern as
test_fleet.py.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import pytest

from machine_learning_replications_tpu.fleet import make_router
from machine_learning_replications_tpu.obs import fleetmetrics, fleettrace
from machine_learning_replications_tpu.obs.reqtrace import (
    FlightRecorder,
    RequestTrace,
)
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
)

import sys
import os

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
from validate_metrics import validate  # noqa: E402


# ---------------------------------------------------------------------------
# clock-offset estimator
# ---------------------------------------------------------------------------


def test_clock_sync_recovers_synthetic_skew():
    """A replica whose perf clock runs 5 s ahead: the midpoint estimate
    recovers the skew to within RTT/2 on the first probe."""
    cs = fleettrace.ClockSync()
    # Probe took 10 ms; replica stamped its clock exactly at the
    # midpoint, so the estimate is exact.
    off = cs.observe("r1", t_send=100.0, t_recv=100.010,
                     replica_clock=105.005)
    assert off == pytest.approx(5.0, abs=1e-9)
    assert cs.offset_s("r1") == pytest.approx(5.0, abs=1e-9)

    # EWMA smoothing: a second, slightly-off sample moves the estimate
    # by alpha * innovation, not to the raw value.
    cs.observe("r1", t_send=101.0, t_recv=101.010,
               replica_clock=106.015)  # raw = 5.010
    expected = 5.0 + fleettrace.ClockSync.EWMA_ALPHA * 0.010
    assert cs.offset_s("r1") == pytest.approx(expected, abs=1e-9)

    snap = cs.snapshot()
    assert snap["r1"]["samples"] == 2
    assert snap["r1"]["rtt_ms"] == pytest.approx(10.0, abs=1e-6)

    cs.forget("r1")
    assert cs.offset_s("r1") is None


def test_clock_sync_negative_skew():
    cs = fleettrace.ClockSync()
    cs.observe("r2", t_send=50.0, t_recv=50.002, replica_clock=20.001)
    assert cs.offset_s("r2") == pytest.approx(-30.0, abs=1e-9)


# ---------------------------------------------------------------------------
# flight-recorder exact lookup (the join's fetch primitive)
# ---------------------------------------------------------------------------


def _finished_trace(rid, status="ok"):
    tr = RequestTrace(rid)
    t0 = tr.t_start
    tr.add_phase("parse", t0, t0 + 0.001)
    tr.finish(status)
    return tr


def test_flight_recorder_lookup_indexes_all_completions():
    rec = FlightRecorder(capacity=4, index_capacity=8)
    for i in range(6):
        rec.record(_finished_trace(f"req-{i}"))
    # Every completion is indexed, not just the tail-sampled ring.
    snap = rec.lookup("req-0")
    assert snap is not None and snap["request_id"] == "req-0"
    assert "t_start_perf" in snap and "phases" in snap
    assert rec.lookup("req-never") is None
    stats = rec.stats()
    assert stats["indexed"] == 6
    assert stats["index_capacity"] == 8


def test_flight_recorder_lookup_evicts_fifo():
    rec = FlightRecorder(capacity=4, index_capacity=3)
    for i in range(5):
        rec.record(_finished_trace(f"req-{i}"))
    assert rec.lookup("req-0") is None  # evicted
    assert rec.lookup("req-1") is None  # evicted
    assert rec.lookup("req-4") is not None
    with pytest.raises(ValueError):
        FlightRecorder(index_capacity=0)


# ---------------------------------------------------------------------------
# exposition merge math (goldens)
# ---------------------------------------------------------------------------


PAGE_R1 = """\
# HELP stub_requests_total Requests served.
# TYPE stub_requests_total counter
stub_requests_total{outcome="ok"} 10
stub_requests_total{outcome="shed"} 2
# HELP stub_queue_depth Admission queue depth.
# TYPE stub_queue_depth gauge
stub_queue_depth 3
# HELP stub_latency_seconds Latency.
# TYPE stub_latency_seconds histogram
stub_latency_seconds_bucket{le="0.01"} 4
stub_latency_seconds_bucket{le="0.1"} 9
stub_latency_seconds_bucket{le="+Inf"} 10
stub_latency_seconds_sum 0.5
stub_latency_seconds_count 10
"""

PAGE_R2 = """\
# HELP stub_requests_total Requests served.
# TYPE stub_requests_total counter
stub_requests_total{outcome="ok"} 7
# HELP stub_queue_depth Admission queue depth.
# TYPE stub_queue_depth gauge
stub_queue_depth 5
# HELP stub_latency_seconds Latency.
# TYPE stub_latency_seconds histogram
stub_latency_seconds_bucket{le="0.01"} 1
stub_latency_seconds_bucket{le="0.1"} 6
stub_latency_seconds_bucket{le="+Inf"} 7
stub_latency_seconds_sum 0.8
stub_latency_seconds_count 7
"""


def _merge(pages, **kw):
    parsed = {
        rid: fleetmetrics.parse_exposition(text)
        for rid, text in pages.items()
    }
    return fleetmetrics.merge_expositions(parsed, **kw)


def test_merge_counter_sum_and_gauge_relabel_goldens():
    merged, rejected = _merge({"r1": PAGE_R1, "r2": PAGE_R2})
    assert rejected == []

    counters = merged["stub_requests_total"]["series"]
    assert counters[(("outcome", "ok"),)] == 17  # summed across replicas
    assert counters[(("outcome", "shed"),)] == 2  # present on r1 only

    gauges = merged["stub_queue_depth"]["series"]
    assert gauges[(("replica", "r1"),)] == 3  # re-emitted, never averaged
    assert gauges[(("replica", "r2"),)] == 5

    hist = merged["stub_latency_seconds"]["series"][()]
    assert hist["buckets"] == {"0.01": 5, "0.1": 15, "+Inf": 17}
    assert hist["sum"] == pytest.approx(1.3)
    assert hist["count"] == 17

    text = fleetmetrics.render_merged(merged)
    assert validate(text) == []  # strict-validator clean
    assert 'stub_requests_total{outcome="ok"} 17' in text
    assert 'stub_queue_depth{replica="r2"} 5' in text


def test_merge_rejects_bucket_mismatch():
    page2 = PAGE_R2.replace('le="0.01"', 'le="0.025"')
    merged, rejected = _merge({"r1": PAGE_R1, "r2": page2})
    assert "stub_latency_seconds" not in merged
    assert {"name": "stub_latency_seconds",
            "reason": "bucket_mismatch"} in rejected
    # The other families still merge — one bad family never poisons
    # the page.
    assert merged["stub_requests_total"]["series"][(("outcome", "ok"),)] \
        == 17
    assert validate(fleetmetrics.render_merged(merged)) == []


def test_merge_rejects_kind_and_label_mismatch():
    gauge_as_counter = (
        "# TYPE stub_queue_depth counter\nstub_queue_depth 4\n"
    )
    merged, rejected = _merge({"r1": PAGE_R1, "r2": gauge_as_counter})
    reasons = {r["name"]: r["reason"] for r in rejected}
    assert reasons["stub_queue_depth"] == "kind_mismatch"

    relabeled = (
        "# TYPE stub_requests_total counter\n"
        'stub_requests_total{outcome="ok",shard="a"} 1\n'
    )
    merged, rejected = _merge({"r1": PAGE_R1, "r2": relabeled})
    reasons = {r["name"]: r["reason"] for r in rejected}
    assert reasons["stub_requests_total"] == "label_mismatch"

    # A replica-side gauge already labeled `replica` would collide with
    # the label the merge appends.
    own_replica = (
        "# TYPE stub_queue_depth gauge\n"
        'stub_queue_depth{replica="imposter"} 9\n'
    )
    merged, rejected = _merge({"r1": PAGE_R1, "r2": own_replica})
    reasons = {r["name"]: r["reason"] for r in rejected}
    assert reasons["stub_queue_depth"] == "label_mismatch"


def test_merge_drops_router_owned_families():
    merged, rejected = _merge(
        {"r1": PAGE_R1}, drop=frozenset({"stub_queue_depth"}),
    )
    assert "stub_queue_depth" not in merged
    assert {"name": "stub_queue_depth",
            "reason": "router_owned"} in rejected


def test_parse_exposition_escapes_and_specials():
    page = (
        "# TYPE weird_gauge gauge\n"
        'weird_gauge{msg="a\\"b\\\\c\\nd"} NaN\n'
        'weird_gauge{msg="inf"} +Inf\n'
    )
    fam = fleetmetrics.parse_exposition(page)["weird_gauge"]
    key = (("msg", 'a"b\\c\nd'),)
    assert fam["series"][key] != fam["series"][key]  # NaN
    assert fam["series"][(("msg", "inf"),)] == float("inf")
    # ... and the round-trip re-escapes cleanly.
    merged, _ = _merge({"r1": page})
    assert validate(fleetmetrics.render_merged(merged)) == []


# ---------------------------------------------------------------------------
# the join (synthetic, injected fetch)
# ---------------------------------------------------------------------------


def _router_sample(rid, replica, t0, phases, total):
    return {
        "request_id": rid, "status": "ok", "t_start_perf": t0,
        "total_seconds": total, "replica": replica, "attempts": 1,
        "phases": {
            name: {"offset_seconds": off, "seconds": dur}
            for name, (off, dur) in phases.items()
        },
    }


def test_join_fleet_trace_offset_corrected_containment():
    """Replica clock 5 s ahead: raw replica stamps land nowhere near the
    router's upstream span; offset-corrected they nest inside it."""
    skew = 5.0
    cs = fleettrace.ClockSync()
    cs.observe("r1", t_send=0.0, t_recv=0.0, replica_clock=skew)

    t0 = 1000.0  # router admission (router clock)
    sample = _router_sample(
        "req-j", "r1", t0,
        {"parse": (0.0, 0.001), "upstream": (0.001, 0.050),
         "respond": (0.051, 0.001)},
        total=0.052,
    )
    # Replica-side: starts 10 ms into the upstream window, 30 ms long —
    # stamped on the REPLICA's (skewed) clock.
    replica_snap = {
        "request_id": "req-j", "status": "ok",
        "t_start_perf": t0 + 0.011 + skew, "total_seconds": 0.030,
        "phases": {
            "parse": {"offset_seconds": 0.0, "seconds": 0.002},
            "device_compute": {"offset_seconds": 0.002, "seconds": 0.020},
            "respond": {"offset_seconds": 0.028, "seconds": 0.002},
        },
        "path": "device",
    }

    def fetch(url, rid, timeout_s):
        assert url == "http://rep:1" and rid == "req-j"
        return replica_snap, "ok"

    export = fleettrace.join_fleet_trace(
        [sample], {"r1": "http://rep:1"}, cs, fetch=fetch,
    )
    other = export["otherData"]
    assert other["results"]["joined"] == 1
    assert other["containment"]["contained"] == 1
    assert other["containment"]["ratio"] == 1.0

    by_name = {}
    for ev in export["traceEvents"]:
        if ev.get("ph") == "X":
            by_name[ev["name"]] = ev
    up = by_name["upstream"]
    rep = by_name["replica r1"]
    # Same lane (the viewers nest positionally on one tid)...
    assert rep["tid"] == up["tid"]
    # ...and the replica interval sits inside upstream on the router's
    # timeline despite the 5 s clock skew.
    assert rep["ts"] >= up["ts"]
    assert rep["ts"] + rep["dur"] <= up["ts"] + up["dur"]
    assert by_name["device_compute"]["dur"] == pytest.approx(20_000, rel=0.01)
    assert rep["args"]["offset_ms"] == pytest.approx(5000.0, abs=1.0)


def test_join_fleet_trace_counts_misses_explicitly():
    cs = fleettrace.ClockSync()
    cs.observe("r1", 0.0, 0.0, 0.0)
    samples = [
        _router_sample("req-a", None, 1.0, {}, 0.01),      # no replica meta
        _router_sample("req-b", "ghost", 1.1, {}, 0.01),   # unknown replica
        _router_sample("req-c", "r2", 1.2, {}, 0.01),      # no offset yet
        _router_sample("req-d", "r1", 1.3, {}, 0.01),      # 404 at replica
    ]

    def fetch(url, rid, timeout_s):
        return None, "no_replica_trace"

    export = fleettrace.join_fleet_trace(
        samples, {"r1": "http://rep:1", "r2": "http://rep:2"}, cs,
        fetch=fetch,
    )
    r = export["otherData"]["results"]
    assert r["no_replica_meta"] == 1
    assert r["unknown_replica"] == 1
    assert r["no_offset"] == 1
    assert r["no_replica_trace"] == 1
    assert r["joined"] == 0
    assert export["otherData"]["containment"]["ratio"] is None


# ---------------------------------------------------------------------------
# scraper staleness (real HTTP, stub registry)
# ---------------------------------------------------------------------------


class _PageApp:
    def __init__(self, text):
        self.text = text

    def handle_request(self, req, rsp):
        if req.path == "/metrics":
            rsp.send(200, self.text.encode(), "text/plain; version=0.0.4")
        else:
            rsp.send_json(404, {"error": "nope"})

    def handle_protocol_error(self, exc, rsp):
        rsp.send_json(exc.code, {"error": exc.message}, close=True)


class _StubRegistry:
    def __init__(self, rows):
        self.rows = rows

    def snapshot(self):
        return self.rows


def test_fleet_scraper_marks_stale_replicas():
    httpd = EventLoopHttpServer(("127.0.0.1", 0), _PageApp(PAGE_R1))
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        live = f"http://127.0.0.1:{httpd.server_address[1]}"
        dead = "http://127.0.0.1:1"  # nothing listens here
        scraper = fleetmetrics.FleetScraper(
            _StubRegistry([
                {"id": "alive", "url": live, "in_rotation": True},
                {"id": "gone", "url": dead, "in_rotation": True},
                {"id": "benched", "url": dead, "in_rotation": False},
            ]),
            timeout_s=2.0,
        )
        text, summary = scraper.render_fleet_page()
        # The dead replica is marked, never silently omitted; the
        # benched one is not in rotation, so it is not scraped at all.
        assert summary["scraped"] == ["alive"]
        assert summary["stale"] == ["gone"]
        assert validate(text) == []
        assert 'fleet_scrape_stale{replica="gone"} 1' in text
        assert 'fleet_scrape_stale{replica="alive"} 0' in text
        assert 'stub_requests_total{outcome="ok"} 10' in text
    finally:
        httpd.server_close()


# ---------------------------------------------------------------------------
# router endpoints end-to-end (stub replicas, real transport)
# ---------------------------------------------------------------------------


class _ObsStubReplica:
    """A stub replica with the telemetry surfaces the fleet plane
    consumes: /readyz echoing clock_perf, /metrics with a fixed page,
    /predict recording a real trace snapshot served back via
    /debug/requests?id=."""

    def __init__(self, rid):
        self.rid = rid
        self.traces = {}
        self.lock = threading.Lock()

    def handle_request(self, req, rsp):
        if req.path == "/readyz":
            rsp.send_json(200, {
                "ready": True, "reasons": [], "replica": self.rid,
                "version": 1, "queue_depth": 0,
                "clock_perf": time.perf_counter(),
            })
        elif req.path == "/metrics":
            rsp.send(200, PAGE_R1.encode(), "text/plain; version=0.0.4")
        elif req.path == "/debug/requests":
            rid = req.query_param("id", "")
            with self.lock:
                snap = self.traces.get(rid)
            if snap is None:
                rsp.send_json(404, {"error": "not indexed"})
            else:
                rsp.send_json(200, {"request": snap})
        elif req.path == "/predict":
            t0 = time.perf_counter()
            time.sleep(0.005)
            t1 = time.perf_counter()
            rid = req.get_header("x-request-id") or "anon"
            with self.lock:
                self.traces[rid] = {
                    "request_id": rid, "status": "ok",
                    "t_start_perf": round(t0, 6),
                    "total_seconds": round(t1 - t0, 6),
                    "phases": {
                        "parse": {"offset_seconds": 0.0, "seconds": 0.001},
                        "host_compute": {
                            "offset_seconds": 0.001,
                            "seconds": round(t1 - t0 - 0.001, 6),
                        },
                    },
                    "path": "host",
                }
            rsp.send_json(
                200, {"probability": 0.5},
                headers={"X-Replica": self.rid, "X-Model-Version": "1"},
                request_id=rid,
            )
        else:
            rsp.send_json(404, {"error": "nope"})

    def handle_protocol_error(self, exc, rsp):
        rsp.send_json(exc.code, {"error": exc.message}, close=True)


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read())


def test_router_fleet_telemetry_endpoints():
    stubs, httpds, members = [], [], []
    for i in range(2):
        stub = _ObsStubReplica(f"r{i + 1}")
        httpd = EventLoopHttpServer(("127.0.0.1", 0), stub)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        stubs.append(stub)
        httpds.append(httpd)
        members.append(
            (stub.rid, f"http://127.0.0.1:{httpd.server_address[1]}")
        )
    router = make_router(
        port=0, replicas=members, probe_interval_s=0.1,
        request_timeout_s=5.0,
    ).start_background()
    try:
        deadline = time.monotonic() + 10
        while router.registry.ready_count() < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.02)
        assert router.registry.ready_count() == 2
        base = f"http://{router.address[0]}:{router.address[1]}"

        # Wait for a clock-offset estimate on every replica (one probe
        # tick each).
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and any(
            router.clock_sync.offset_s(rid) is None for rid, _ in members
        ):
            time.sleep(0.02)

        ids = []
        for i in range(8):
            rid = f"obs-e2e-{i}"
            req = urllib.request.Request(
                base + "/predict", data=b'{"x": 1}',
                headers={"Content-Type": "application/json",
                         "X-Request-Id": rid},
            )
            with urllib.request.urlopen(req, timeout=10.0) as resp:
                assert resp.status == 200
            ids.append(rid)

        # -- /debug/requests?id= on the router ---------------------------
        status, body = _get_json(
            base + f"/debug/requests?id={ids[0]}"
        )
        assert status == 200
        assert body["request"]["request_id"] == ids[0]
        assert body["request"]["replica"] in ("r1", "r2")
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _get_json(base + "/debug/requests?id=never-seen")
        assert exc_info.value.code == 404
        assert "error" in json.loads(exc_info.value.read())

        # -- /fleet/metrics ----------------------------------------------
        with urllib.request.urlopen(
            base + "/fleet/metrics", timeout=10.0
        ) as resp:
            page = resp.read().decode()
        assert validate(page) == []
        # Merged replica families, summed across the two stubs...
        assert 'stub_requests_total{outcome="ok"} 20' in page
        # ...the router's own families appended...
        assert "fleet_requests_total" in page
        # ...including the fleet-level SLO fed from the router's stream
        # and the scrape-health families updated by this very scrape.
        assert 'fleet_slo_requests_total{slo="availability"}' in page
        assert 'fleet_scrape_stale{replica="r1"} 0' in page

        # -- /fleet/trace -------------------------------------------------
        status, export = _get_json(base + "/fleet/trace?n=64")
        assert status == 200
        other = export["otherData"]
        assert other["joined"] >= 1
        assert other["containment"]["contained"] == other["joined"]
        cats = {
            ev.get("cat") for ev in export["traceEvents"]
            if ev.get("ph") == "X"
        }
        assert {"router", "replica"} <= cats
    finally:
        router.shutdown()
        for h in httpds:
            h.server_close()
