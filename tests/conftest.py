"""Test harness: force an 8-device virtual CPU mesh.

The reference has no tests at all (SURVEY.md §4); this suite is the
framework's formalization of its implicit validation protocol, plus kernel
unit tests and multi-chip tests. Tests run on CPU with 8 virtual XLA devices
(`xla_force_host_platform_device_count`) — the TPU-world fake backend — so the
sharded psum/shard_map paths are exercised without a pod.

Env vars must be set before jax initializes its backends, hence this guard at
conftest import time (pytest imports conftest before any test module).
"""

import os

# The ambient environment registers the 'axon' TPU backend from a
# sitecustomize that imports jax at interpreter startup, so plain env-var
# setdefaults are too late; jax.config.update still works because backend
# *initialization* is lazy (first jax.devices() call).
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Double precision on CPU so differential tests against float64 sklearn are
# meaningful at tight tolerances.
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def cohort():
    """Small synthetic cohort with missingness, shared across tests."""
    from machine_learning_replications_tpu.data import make_cohort

    return make_cohort(n=500, seed=2020, missing_rate=0.05)


@pytest.fixture(scope="session")
def cohort_full():
    """Full-size (1427) synthetic cohort, no missingness."""
    from machine_learning_replications_tpu.data import make_cohort

    return make_cohort(n=1427, seed=2020, missing_rate=0.0)


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(0)
