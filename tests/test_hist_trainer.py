"""Distributed level-wise trainer: sharded == single-device, any depth.

Runs on the virtual 8-device CPU mesh (conftest) — the multi-chip fake
backend of SURVEY.md §4.
"""

import numpy as np
import pytest

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.data.schema import selected_indices
from machine_learning_replications_tpu.models import gbdt
from machine_learning_replications_tpu.parallel import hist_trainer, make_mesh


@pytest.mark.parametrize(
    "depth,backend,mesh_shape",
    [
        (1, "xla", (8, 1)),
        (2, "xla", (8, 1)),
        (3, "xla", (4, 2)),   # model axis replicated, exercised anyway
        (2, "pallas", (8, 1)),  # Pallas kernel inside shard_map
    ],
)
def test_sharded_matches_single_device(cohort_full, depth, backend, mesh_shape):
    X, y, _ = cohort_full
    Xs = X[:, selected_indices()]
    cfg = GBDTConfig(
        n_estimators=6, max_depth=depth, splitter="hist", n_bins=32,
        histogram_backend=backend,
    )
    mesh = make_mesh(data=mesh_shape[0], model=mesh_shape[1])
    ps, auxs = hist_trainer.fit(mesh, Xs, y, cfg)
    p1, aux1 = gbdt.fit(Xs, y, cfg)
    # Model-level parity: psum reduction order can flip argmax between
    # *equivalent* near-tied splits, so structural equality is not a sound
    # assertion — deviance and predictions are (cf. test_pallas_histogram).
    np.testing.assert_allclose(
        auxs["train_deviance"], aux1["train_deviance"], rtol=1e-9
    )
    from machine_learning_replications_tpu.models import tree

    np.testing.assert_allclose(
        np.asarray(tree.predict_proba1(ps, Xs)),
        np.asarray(tree.predict_proba1(p1, Xs)),
        rtol=1e-9,
        atol=1e-12,
    )


def test_uneven_rows_padding(cohort_full):
    """Row counts not divisible by the data axis: padding must not leak."""
    X, y, _ = cohort_full
    Xs = X[:503, selected_indices()]  # prime-ish row count over 8 shards
    ys = y[:503]
    cfg = GBDTConfig(n_estimators=4, max_depth=2, splitter="hist", n_bins=16)
    mesh = make_mesh(data=8, model=1)
    ps, auxs = hist_trainer.fit(mesh, Xs, ys, cfg)
    p1, aux1 = gbdt.fit(Xs, ys, cfg)
    np.testing.assert_allclose(
        auxs["train_deviance"], aux1["train_deviance"], rtol=1e-9
    )
    np.testing.assert_array_equal(np.asarray(ps.feature), np.asarray(p1.feature))
