"""Bulk-scoring pipeline tests (score/ + cli score — docs/SCORING.md).

The load-bearing contracts, each pinned here:

  * **Parity** — `cli score` output is bit-identical to the `cli predict`
    oracle on the same rows, for the contract route (JSONL patients /
    bare ensembles) and the raw-x64 route (.mat through the full
    pipeline), whatever the chunking.
  * **Resume** — a run killed mid-cohort restarts at the last committed
    chunk and produces byte-identical output to an uninterrupted run: no
    duplicated rows, no missing rows, quarantine sidecar included.
  * **Malformed-row policy** — bad lines quarantine with line numbers and
    the run continues; the bounded error budget aborts loudly.
  * **Overlap is a pure optimization** — the overlapped pipeline's output
    equals the sequential ablation's, byte for byte.
  * **Telemetry** — score_* families are strict-exposition-clean and the
    cohort-level quality snapshot runs over the scored population.
"""

import json
import os
import sys
import warnings

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
try:
    import validate_metrics
finally:
    sys.path.pop(0)

import jax.numpy as jnp

from machine_learning_replications_tpu.data import make_cohort
from machine_learning_replications_tpu.data.schema import (
    SELECTED_17,
    selected_indices,
)
from machine_learning_replications_tpu.score import (
    JsonlCohortSource,
    ScoreBudgetExceeded,
    ScorePipeline,
    ScoreResumeError,
    open_cohort,
)
from machine_learning_replications_tpu.score.pipeline import ScoreInterrupted


# ---------------------------------------------------------------------------
# fixtures: a fast real ensemble + a hand-assembled full pipeline
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def stacking_params():
    """sklearn-fitted stacking ensemble imported into our pytrees — the
    contract-route (17-column) scoring family."""
    from sklearn.ensemble import (
        GradientBoostingClassifier,
        StackingClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    from machine_learning_replications_tpu.persist import import_stacking

    rng = np.random.default_rng(7)
    n, f = 200, 17
    X = rng.normal(size=(n, f))
    X[:, :10] = (X[:, :10] > 0.3).astype(float)
    y = (X @ rng.normal(size=f) + rng.normal(size=n) > 0.2).astype(float)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = StackingClassifier(
            estimators=[
                ("svc", make_pipeline(
                    StandardScaler(), SVC(probability=True, random_state=0)
                )),
                ("gbc", GradientBoostingClassifier(
                    n_estimators=5, max_depth=1, random_state=0)),
                ("lg", LogisticRegression()),
            ],
            final_estimator=LogisticRegression(),
        ).fit(X, y)
    return import_stacking(clf)


@pytest.fixture(scope="module")
def pipeline_params(stacking_params):
    """A full PipelineParams assembled from real fitted pieces (KNN
    imputer over a NaN-bearing cohort, contract support mask, the module's
    sklearn ensemble, a genuine reference profile) — the x64/pipeline
    scoring family, WITHOUT paying a whole fit_pipeline in tier-1 time."""
    from machine_learning_replications_tpu.models import (
        knn_impute, pipeline, stacking,
    )
    from machine_learning_replications_tpu.obs import quality

    X64, y, _ = make_cohort(n=300, seed=3, missing_rate=0.05)
    imp, X_imp = knn_impute.fit_transform(jnp.asarray(X64))
    mask = np.zeros(64, bool)
    mask[selected_indices()] = True
    X17 = np.asarray(X_imp)[:, np.where(mask)[0]]
    scores = np.asarray(
        stacking.predict_proba1(stacking_params, jnp.asarray(X17))
    )
    prof = quality.build_reference_profile(X17, scores, y=y)
    return pipeline.PipelineParams(
        imputer=imp,
        support_mask=jnp.asarray(mask),
        ensemble=stacking_params,
        quality={k: jnp.asarray(v) for k, v in prof.items()},
    )


@pytest.fixture(scope="module")
def cohort_rows():
    """500 contract-order rows drawn from the schema-matched generator."""
    X64, _, _ = make_cohort(n=500, seed=11, missing_rate=0.0)
    return X64[:, selected_indices()]


def _write_jsonl(path, rows, bad_at=()):
    """Patient-dict JSONL; ``bad_at`` inserts malformed lines BEFORE the
    given 0-based row positions. Returns total line count."""
    bad_cycle = [
        "{definitely not json",
        json.dumps({"Gender": 1}),                      # missing variables
        json.dumps(dict(zip(SELECTED_17, [None] * 17))),  # non-numeric
        "",                                              # empty line
    ]
    lines = 0
    with open(path, "w") as f:
        for i, row in enumerate(rows):
            if i in bad_at:
                f.write(bad_cycle[lines % len(bad_cycle)] + "\n")
                lines += 1
            f.write(json.dumps(
                {k: float(v) for k, v in zip(SELECTED_17, row)}
            ) + "\n")
            lines += 1
    return lines


def _run(params, cohort_path, out_dir, chunk_rows=64, **kw):
    kw.setdefault("model_digest", "test-model")
    kw.setdefault("rows_per_shard", 150)
    src = open_cohort(str(cohort_path), chunk_rows)
    return ScorePipeline(params, src, str(out_dir), **kw).run()


def _read_scores(out_dir):
    """All committed score records across shards, in order."""
    recs = []
    for name in sorted(os.listdir(out_dir)):
        if name.startswith("scores-") and name.endswith(".jsonl"):
            with open(os.path.join(out_dir, name)) as f:
                recs += [json.loads(line) for line in f]
    return recs


def _tree_bytes(out_dir):
    """Concatenated bytes of every output shard + the quarantine sidecar
    — the byte-identical-resume comparison domain."""
    out = b""
    names = sorted(
        n for n in os.listdir(out_dir)
        if n.startswith("scores-") or n == "quarantine.jsonl"
    )
    for name in names:
        with open(os.path.join(out_dir, name), "rb") as f:
            out += name.encode() + b"\0" + f.read() + b"\0"
    return out


# ---------------------------------------------------------------------------
# reader + quarantine policy
# ---------------------------------------------------------------------------


def test_jsonl_reader_chunks_lines_and_quarantine(tmp_path, cohort_rows):
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:100], bad_at=(5, 50))
    src = JsonlCohortSource(str(path), chunk_rows=32)
    chunks = [src.parse(b) for b in src.blocks()]
    # 102 lines → 32/32/32/6; every line consumed exactly once.
    assert [c.lines_consumed for c in chunks] == [32, 32, 32, 6]
    assert sum(c.n_rows for c in chunks) == 100
    assert sum(len(c.bad) for c in chunks) == 2
    # Quarantine entries carry the malformed lines' 1-based numbers: the
    # inserts landed before rows 5 and 50, i.e. lines 6 and 52.
    bad_lines = [line for c in chunks for (line, _err, _raw) in c.bad]
    assert bad_lines == [6, 52]
    # Valid rows carry their own input line numbers, gaps skipped.
    all_lines = np.concatenate([c.line_nos for c in chunks])
    assert len(all_lines) == 100
    assert 6 not in all_lines and 52 not in all_lines
    # Values round-trip exactly.
    row0 = chunks[0].X[0]
    np.testing.assert_array_equal(row0, cohort_rows[0])


def test_reader_skip_lines_resume_alignment(tmp_path, cohort_rows):
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:100])
    src = JsonlCohortSource(str(path), chunk_rows=32)
    full = [src.parse(b) for b in src.blocks()]
    resumed = [src.parse(b) for b in src.blocks(skip_lines=64, start_seq=2)]
    assert [c.seq for c in resumed] == [2, 3]
    np.testing.assert_array_equal(resumed[0].X, full[2].X)
    np.testing.assert_array_equal(resumed[0].line_nos, full[2].line_nos)


def test_budget_abort(tmp_path, stacking_params, cohort_rows):
    path = tmp_path / "bad.jsonl"
    _write_jsonl(path, cohort_rows[:60], bad_at=(1, 2, 3, 4, 5))
    with pytest.raises(ScoreBudgetExceeded):
        _run(
            stacking_params, path, tmp_path / "out",
            chunk_rows=16, max_bad_rows=3, overlap=False,
        )
    # The run aborted resumable: nothing says 'done'.
    prog = json.load(open(tmp_path / "out" / "progress.json")) if (
        tmp_path / "out" / "progress.json"
    ).exists() else {"done": False}
    assert not prog.get("done")


def test_budget_abort_flushes_triggering_rows(
    tmp_path, stacking_params, cohort_rows
):
    """The chunk that blows the budget never commits, but its offending
    rows must still reach the sidecar the abort message points at."""
    path = tmp_path / "bad.jsonl"
    _write_jsonl(path, cohort_rows[:40], bad_at=(2, 3))
    out = tmp_path / "out"
    with pytest.raises(ScoreBudgetExceeded):
        _run(
            stacking_params, path, out, chunk_rows=64, max_bad_rows=1,
            overlap=False,
        )
    entries = [json.loads(line) for line in open(out / "quarantine.jsonl")]
    assert len(entries) == 2 and all(e["error"] for e in entries)


def test_bare_ensemble_mat_nan_rows_quarantined(tmp_path, stacking_params):
    """A 17-wide .mat cohort with NaNs scored by a bare ensemble (no
    imputer) must quarantine the non-finite rows — not write invalid
    JSON shard lines like {"p1": nan}."""
    scipy_io = pytest.importorskip("scipy.io")
    rng = np.random.default_rng(4)
    X = rng.normal(size=(50, 17))
    X[7, 3] = np.nan
    X[31, 0] = np.nan
    path = tmp_path / "cohort17.mat"
    scipy_io.savemat(str(path), {
        "data_tb": X, "clin_var_names": np.empty((1, 0), object),
    })
    out = tmp_path / "out"
    summary = _run(stacking_params, path, out, chunk_rows=16)
    assert summary["rows"] == 48 and summary["bad_rows"] == 2
    recs = _read_scores(out)  # every line must be strict JSON
    assert len(recs) == 48
    assert all(np.isfinite(r["p1"]) for r in recs)
    quar = [json.loads(line) for line in open(out / "quarantine.jsonl")]
    assert {q["line"] for q in quar} == {8, 32}  # 1-based rows
    assert all("non-finite" in q["error"] for q in quar)


def test_fresh_start_clears_stale_summary(
    tmp_path, stacking_params, cohort_rows
):
    """A new run into a directory holding a FINISHED run's outputs must
    not leave the old summary/quality behind: an early abort would
    otherwise attribute the previous run's verdict to this one."""
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:200])
    out = tmp_path / "out"
    _run(stacking_params, path, out, chunk_rows=64)
    assert (out / "summary.json").exists()
    with pytest.raises(ScoreInterrupted):
        _run(
            stacking_params, path, out, chunk_rows=64,
            _interrupt_after_chunks=1,
        )
    assert not (out / "summary.json").exists()


def test_quarantine_sidecar_contents(tmp_path, stacking_params, cohort_rows):
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:80], bad_at=(10, 40))
    out = tmp_path / "out"
    summary = _run(
        stacking_params, path, out, chunk_rows=32, overlap=False,
    )
    assert summary["bad_rows"] == 2
    assert summary["rows"] == 80
    entries = [
        json.loads(line) for line in open(out / "quarantine.jsonl")
    ]
    # Inserts landed before rows 10 and 40 → input lines 11 and 42
    # (the second insert follows 40 valid rows + the first bad line).
    assert [e["line"] for e in entries] == [11, 42]
    assert all(e["error"] for e in entries)


# ---------------------------------------------------------------------------
# parity: bit-identical to the cli predict oracle, both routes
# ---------------------------------------------------------------------------


def test_contract_route_parity_bitwise(
    tmp_path, stacking_params, cohort_rows
):
    from machine_learning_replications_tpu.models import stacking

    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows)
    out = tmp_path / "out"
    summary = _run(stacking_params, path, out, chunk_rows=64)
    assert summary["rows"] == len(cohort_rows)
    expect = np.asarray(
        stacking.predict_proba1(stacking_params, jnp.asarray(cohort_rows))
    )
    got = np.asarray([r["p1"] for r in _read_scores(out)])
    np.testing.assert_array_equal(got, expect)  # bitwise, not approx


def test_pipeline_route_parity_bitwise(
    tmp_path, pipeline_params, cohort_rows
):
    """JSONL contract dicts through a full-pipeline checkpoint: embed at
    schema positions → KNN-impute → support gather → stacked blend — must
    equal pipeline_predict_proba1_contract (the cli predict --model
    route) bit for bit."""
    from machine_learning_replications_tpu.models import pipeline

    rows = cohort_rows[:200]
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, rows)
    out = tmp_path / "out"
    summary = _run(pipeline_params, path, out, chunk_rows=64)
    assert summary["route"] == "contract"
    expect = np.asarray(
        pipeline.pipeline_predict_proba1_contract(pipeline_params, rows)
    )
    got = np.asarray([r["p1"] for r in _read_scores(out)])
    np.testing.assert_array_equal(got, expect)


def test_mat_x64_route_parity_bitwise(tmp_path, pipeline_params):
    """A reference-layout .mat cohort (64 raw columns + outcome, NaNs for
    the imputer) through the x64 route equals pipeline_predict_proba1."""
    scipy_io = pytest.importorskip("scipy.io")
    from machine_learning_replications_tpu.data.schema import variable_names
    from machine_learning_replications_tpu.models import pipeline

    X64, y, _ = make_cohort(n=150, seed=23, missing_rate=0.04)
    path = tmp_path / "cohort.mat"
    scipy_io.savemat(str(path), {
        "data_tb": np.concatenate([X64, y.reshape(-1, 1)], axis=1),
        "clin_var_names": np.array([variable_names()], dtype=object),
    })
    out = tmp_path / "out"
    summary = _run(pipeline_params, path, out, chunk_rows=64)
    assert summary["route"] == "x64"
    assert summary["rows"] == 150
    expect = np.asarray(
        pipeline.pipeline_predict_proba1(pipeline_params, X64)
    )
    got = np.asarray([r["p1"] for r in _read_scores(out)])
    np.testing.assert_array_equal(got, expect)


def test_x64_route_requires_pipeline_params(tmp_path, stacking_params):
    scipy_io = pytest.importorskip("scipy.io")
    X64, _, _ = make_cohort(n=20, seed=5, missing_rate=0.0)
    path = tmp_path / "cohort.mat"
    scipy_io.savemat(str(path), {
        "data_tb": X64, "clin_var_names": np.empty((1, 0), object),
    })
    with pytest.raises(TypeError, match="PipelineParams"):
        _run(stacking_params, path, tmp_path / "out", overlap=False)


# ---------------------------------------------------------------------------
# overlap vs sequential, shards, compile bound
# ---------------------------------------------------------------------------


def test_overlap_equals_sequential_bytes(
    tmp_path, stacking_params, cohort_rows
):
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows, bad_at=(17, 333))
    seq = _run(
        stacking_params, path, tmp_path / "seq", chunk_rows=64,
        overlap=False,
    )
    ovl = _run(
        stacking_params, path, tmp_path / "ovl", chunk_rows=64,
        overlap=True, parse_workers=3, prefetch=3,
    )
    assert seq["output_sha256"] == ovl["output_sha256"]
    assert _tree_bytes(tmp_path / "seq") == _tree_bytes(tmp_path / "ovl")
    assert ovl["rows"] == seq["rows"] == len(cohort_rows)
    # Per-stage accounting exists in both modes.
    for s in (seq, ovl):
        assert set(s["stage_seconds"]) >= {"read", "parse", "device", "write"}


def test_process_parse_mode_identical(tmp_path, stacking_params, cohort_rows):
    """parse_procs swaps the parse threads for spawned worker processes
    (GIL-free ingest); the output — shards and quarantine sidecar — must
    be byte-identical to the thread mode's."""
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:200], bad_at=(30, 90))
    thr = _run(stacking_params, path, tmp_path / "thr", chunk_rows=64)
    proc = _run(
        stacking_params, path, tmp_path / "proc", chunk_rows=64,
        parse_procs=1,
    )
    assert proc["parse_procs"] == 1 and thr["parse_procs"] == 0
    assert proc["output_sha256"] == thr["output_sha256"]
    assert _tree_bytes(tmp_path / "proc") == _tree_bytes(tmp_path / "thr")
    assert proc["bad_rows"] == 2


def test_shard_rotation_and_row_ids(tmp_path, stacking_params, cohort_rows):
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows)
    out = tmp_path / "out"
    summary = _run(
        stacking_params, path, out, chunk_rows=64, rows_per_shard=120,
    )
    # 500 rows over 120-row shards → 5 shards (120×4 + 20).
    assert [s["rows"] for s in summary["shards"]] == [120, 120, 120, 120, 20]
    recs = _read_scores(out)
    assert [r["row"] for r in recs] == list(range(500))
    assert [r["line"] for r in recs] == list(range(1, 501))
    for s in summary["shards"]:
        assert os.path.getsize(out / s["name"]) == s["bytes"]


def test_mesh_sharded_route(tmp_path, stacking_params, cohort_rows):
    """--mesh routes the stacked pass through the row-sharded predict
    tail (apply_rows_sharded over the conftest 8-virtual-device mesh);
    the scored cohort must match the single-device oracle."""
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.parallel import make_mesh

    rows = cohort_rows[:200]
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, rows)
    out = tmp_path / "out"
    summary = _run(
        stacking_params, path, out, chunk_rows=64, mesh=make_mesh(),
    )
    assert summary["mesh"] and summary["rows"] == 200
    expect = np.asarray(
        stacking.predict_proba1(stacking_params, jnp.asarray(rows))
    )
    got = np.asarray([r["p1"] for r in _read_scores(out)])
    np.testing.assert_allclose(got, expect, rtol=0, atol=1e-12)


def test_fixed_chunk_shape_compile_bound(
    tmp_path, stacking_params, cohort_rows
):
    """Every chunk runs at ONE padded shape, so a second cohort scored in
    the same process compiles nothing new — the engine's
    one-compile-per-bucket bound at chunk granularity."""
    from machine_learning_replications_tpu.obs import jaxmon

    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:300])
    _run(stacking_params, path, tmp_path / "warm", chunk_rows=64)
    before = jaxmon.compile_count()
    _run(stacking_params, path, tmp_path / "again", chunk_rows=64)
    assert jaxmon.compile_count() == before


# ---------------------------------------------------------------------------
# resume
# ---------------------------------------------------------------------------


def test_kill_resume_byte_identical(tmp_path, stacking_params, cohort_rows):
    from machine_learning_replications_tpu.obs import journal

    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows, bad_at=(100, 260))
    golden = _run(
        stacking_params, path, tmp_path / "golden", chunk_rows=64,
    )
    out = tmp_path / "out"
    with pytest.raises(ScoreInterrupted):
        _run(
            stacking_params, path, out, chunk_rows=64,
            _interrupt_after_chunks=3,
        )
    prog = json.load(open(out / "progress.json"))
    assert prog["chunks"] >= 3 and not prog["done"]
    jrn_path = tmp_path / "resume.jsonl"
    jrn = journal.RunJournal(str(jrn_path), command="score")
    journal.set_journal(jrn)
    try:
        resumed = _run(stacking_params, path, out, chunk_rows=64)
    finally:
        journal.set_journal(None)
        jrn.close()
    assert resumed["resumed"] and resumed["resumed_chunks"] >= 3
    assert resumed["rows"] == golden["rows"] == len(cohort_rows)
    assert resumed["output_sha256"] == golden["output_sha256"]
    assert _tree_bytes(out) == _tree_bytes(tmp_path / "golden")
    events = [json.loads(line) for line in open(jrn_path)]
    kinds = [e.get("kind") for e in events]
    assert "score_resume" in kinds and "score_done" in kinds
    assert kinds.count("score_chunk") == resumed["chunks"] - resumed[
        "resumed_chunks"
    ]


def test_resume_truncates_uncommitted_tail(
    tmp_path, stacking_params, cohort_rows
):
    """A crash AFTER appending but BEFORE the manifest commit (the real
    kill -9 window) leaves stray bytes past the committed prefix; resume
    must truncate them, not double-score."""
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:300])
    golden = _run(stacking_params, path, tmp_path / "golden", chunk_rows=64)
    out = tmp_path / "out"
    with pytest.raises(ScoreInterrupted):
        _run(
            stacking_params, path, out, chunk_rows=64,
            _interrupt_after_chunks=2,
        )
    # Emulate the torn post-commit write.
    shard = sorted(
        n for n in os.listdir(out) if n.startswith("scores-")
    )[-1]
    with open(out / shard, "ab") as f:
        f.write(b'{"row":999999,"line":999999,"p1":0.5}\n')
    resumed = _run(stacking_params, path, out, chunk_rows=64)
    assert resumed["output_sha256"] == golden["output_sha256"]
    assert _tree_bytes(out) == _tree_bytes(tmp_path / "golden")


def test_resume_fingerprint_mismatch(tmp_path, stacking_params, cohort_rows):
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:200])
    out = tmp_path / "out"
    with pytest.raises(ScoreInterrupted):
        _run(
            stacking_params, path, out, chunk_rows=64,
            _interrupt_after_chunks=1,
        )
    # Different chunk geometry → different commit points → refuse.
    with pytest.raises(ScoreResumeError, match="chunk_rows"):
        _run(stacking_params, path, out, chunk_rows=32)
    # Different model identity → refuse.
    with pytest.raises(ScoreResumeError, match="params"):
        _run(
            stacking_params, path, out, chunk_rows=64,
            model_digest="other-model",
        )
    # --fresh discards and completes.
    summary = _run(
        stacking_params, path, out, chunk_rows=32, fresh=True,
    )
    assert not summary["resumed"] and summary["rows"] == 200


# ---------------------------------------------------------------------------
# telemetry: metrics exposition + cohort quality
# ---------------------------------------------------------------------------


def test_score_metrics_exposition_valid(
    tmp_path, stacking_params, cohort_rows
):
    from machine_learning_replications_tpu.obs.registry import REGISTRY

    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:200], bad_at=(3,))
    _run(stacking_params, path, tmp_path / "out", chunk_rows=64)
    text = REGISTRY.render_prometheus()
    assert validate_metrics.validate(text) == []
    for family in (
        "score_rows_total", "score_chunks_total",
        "score_quarantined_rows_total", "score_chunk_seconds",
        "score_queue_depth", "score_stage_seconds_total",
    ):
        assert family in text


def test_cohort_quality_snapshot(tmp_path, pipeline_params, cohort_rows):
    rows = cohort_rows[:250]
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, rows)
    out = tmp_path / "out"
    summary = _run(
        pipeline_params, path, out, chunk_rows=64, quality_window=4096,
    )
    q = summary["quality"]
    assert q is not None and q["enabled"]
    assert q["status"] in ("ok", "warn", "alert")
    assert q["rows"] == 250
    snap = json.load(open(out / "quality.json"))
    assert snap["rows_total"] == 250
    assert len(snap["features"]) == 17
    # Feature labels are the model's own selected schema variables.
    names = {f["name"] for f in snap["features"]}
    assert "Max_Wall_Thick" in names


def test_quality_absent_for_bare_ensemble(
    tmp_path, stacking_params, cohort_rows
):
    path = tmp_path / "cohort.jsonl"
    _write_jsonl(path, cohort_rows[:60])
    summary = _run(
        stacking_params, path, tmp_path / "out", chunk_rows=64,
        overlap=False,
    )
    assert summary["quality"] is None
    assert not (tmp_path / "out" / "quality.json").exists()


# ---------------------------------------------------------------------------
# cli end-to-end (in-process main), incl. the cli predict join
# ---------------------------------------------------------------------------


def test_cli_score_end_to_end(
    tmp_path, pipeline_params, cohort_rows, capsys
):
    from machine_learning_replications_tpu import cli
    from machine_learning_replications_tpu.persist import orbax_io

    ckpt = tmp_path / "ckpt"
    orbax_io.save_model(str(ckpt), pipeline_params)
    rows = cohort_rows[:130]
    cohort = tmp_path / "cohort.jsonl"
    _write_jsonl(cohort, rows, bad_at=(7,))
    out = tmp_path / "out"
    metrics = tmp_path / "metrics.txt"
    rc = cli.main([
        "score", "--model", str(ckpt), "--cohort", str(cohort),
        "--out", str(out), "--chunk-rows", "64",
        "--quality-window", "4096", "--metrics-out", str(metrics),
    ])
    assert rc == 0
    printed = capsys.readouterr()
    assert "scored 130 rows" in printed.out
    summary = json.load(open(out / "summary.json"))
    assert summary["rows"] == 130 and summary["bad_rows"] == 1
    assert validate_metrics.validate(open(metrics).read()) == []

    # The cli predict join: the same patient through `predict --model`
    # prints the same probability the score shard recorded.
    recs = _read_scores(out)
    pick = recs[41]
    patient = tmp_path / "patient.json"
    with open(patient, "w") as f:
        json.dump(
            {k: float(v) for k, v in zip(SELECTED_17, rows[41])}, f
        )
    rc = cli.main([
        "predict", "--model", str(ckpt), "--patient", str(patient),
    ])
    assert rc == 0
    printed = capsys.readouterr().out
    assert f"{100.0 * pick['p1']:.2f} %" in printed
