"""Multi-chip tests on the 8-device virtual CPU mesh (SURVEY.md §4).

The sharded stump trainer must produce the *same forest* as the
single-device trainer — communication (psum of histogram partials,
all_gather of per-shard split bests) must be semantically invisible.
"""

import numpy as np
import pytest

import jax

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models import gbdt, tree
from machine_learning_replications_tpu.parallel import make_mesh, stump_trainer


@pytest.fixture(scope="module")
def train_data():
    rng = np.random.default_rng(13)
    n, f = 700, 17
    X = rng.normal(size=(n, f))
    X[:, :12] = (X[:, :12] > 0.4).astype(float)
    X[:, 12:] = np.round(X[:, 12:] * 6) / 3
    w = rng.normal(size=f)
    y = (X @ w + 0.8 * rng.normal(size=n) > 0.3).astype(float)
    return X, y


@pytest.mark.parametrize("data,model", [(8, 1), (4, 2), (2, 4), (1, 1)])
def test_sharded_equals_single_device(train_data, data, model):
    if len(jax.devices()) < data * model:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    cfg = GBDTConfig(n_estimators=30, max_depth=1)
    ref, aux_ref = gbdt.fit(X, y, cfg)
    mesh = make_mesh(data=data, model=model)
    sh, aux_sh = stump_trainer.fit(mesh, X, y, cfg)

    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))
    np.testing.assert_allclose(
        np.asarray(sh.threshold), np.asarray(ref.threshold), rtol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(sh.value), np.asarray(ref.value), rtol=1e-9, atol=1e-12
    )
    np.testing.assert_allclose(
        aux_sh["train_deviance"], aux_ref["train_deviance"], rtol=1e-9
    )


def test_sharded_matches_sklearn(train_data):
    from sklearn.ensemble import GradientBoostingClassifier

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    sk = GradientBoostingClassifier(n_estimators=25, max_depth=1, random_state=2020).fit(X, y)
    mesh = make_mesh(data=4, model=2)
    params, _ = stump_trainer.fit(mesh, X, y, GBDTConfig(n_estimators=25, max_depth=1))
    np.testing.assert_allclose(
        np.asarray(tree.raw_score(params, X[:100])),
        sk.decision_function(X[:100]),
        rtol=1e-9,
    )


def _assert_buffers_replicated(mesh, X, y, cfg):
    """Every device must hold bit-identical replicas of each output — the
    P() out_spec's claim, which padded model shards once silently violated."""
    for arr in stump_trainer._fit_raw(mesh, X, y, cfg):
        shards = list(arr.addressable_shards)
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            np.testing.assert_array_equal(np.asarray(s.data), ref)


@pytest.mark.parametrize("data,model", [(2, 4), (1, 8)])
def test_padded_model_shards_replicated(train_data, data, model):
    # F=5 on model=4 → F_pad=8, shard 3 owns only padded sort slots; on
    # model=8 → shards 5..7 fully padded. Outputs must still be replicated
    # and equal to the single-device forest.
    if len(jax.devices()) < data * model:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    X5 = X[:, :5]
    cfg = GBDTConfig(n_estimators=12, max_depth=1)
    ref, aux_ref = gbdt.fit(X5, y, cfg)
    mesh = make_mesh(data=data, model=model)
    sh, aux = stump_trainer.fit(mesh, X5, y, cfg)
    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))
    np.testing.assert_allclose(np.asarray(sh.value), np.asarray(ref.value), rtol=1e-9)
    np.testing.assert_allclose(
        aux["train_deviance"], aux_ref["train_deviance"], rtol=1e-9
    )
    _assert_buffers_replicated(mesh, X5, y, cfg)


def test_full_mesh_buffers_replicated(train_data):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    mesh = make_mesh(data=4, model=2)
    _assert_buffers_replicated(mesh, X, y, GBDTConfig(n_estimators=10, max_depth=1))


def test_uneven_rows_padding(train_data):
    # 697 rows over 8 shards → 88-row shards, 7 fabricated padding rows
    X, y = train_data
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X697, y697 = X[:697], y[:697]
    cfg = GBDTConfig(n_estimators=10, max_depth=1)
    ref, _ = gbdt.fit(X697, y697, cfg)
    mesh = make_mesh(data=8, model=1)
    sh, _ = stump_trainer.fit(mesh, X697, y697, cfg)
    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))
    np.testing.assert_allclose(np.asarray(sh.value), np.asarray(ref.value), rtol=1e-9)


def test_sample_weight_equals_subset_fit(train_data):
    """A 0/1-weighted sharded fit must equal a single-device fit on the
    physical subset (how the stacking CV's fold fits run under the mesh).
    Bins come from the full matrix in both cases, as fit_folds does."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from machine_learning_replications_tpu.ops import binning

    X, y = train_data
    w = (np.arange(X.shape[0]) % 4 != 0).astype(float)
    cfg = GBDTConfig(n_estimators=15, max_depth=1, splitter="hist")
    bins = binning.bin_features(X, 256)
    mesh = make_mesh(data=4, model=2)
    sh, _ = stump_trainer.fit(mesh, X, y, cfg, bins=bins, sample_weight=w)
    sub_bins = binning.BinnedFeatures(
        binned=bins.binned[w > 0], thresholds=bins.thresholds, n_bins=bins.n_bins
    )
    ref, _ = gbdt.fit(X[w > 0], y[w > 0], cfg, bins=sub_bins)
    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))
    np.testing.assert_allclose(np.asarray(sh.value), np.asarray(ref.value),
                               rtol=1e-9, atol=1e-12)
    np.testing.assert_allclose(float(sh.init_raw), float(ref.init_raw), rtol=1e-12)


def test_layout_memory_guard(train_data):
    """Above the per-shard layout budget the trainer must refuse with
    actionable sizing advice, not OOM (VERDICT r2 weak #5)."""
    X, y = train_data
    mesh = make_mesh(data=2, model=1)
    with pytest.raises(RuntimeError, match="hist|data shards"):
        stump_trainer.fit(
            mesh, X, y, GBDTConfig(n_estimators=2, max_depth=1),
            max_layout_bytes=64,
        )
    # fit_gbdt_sharded falls back to the histogram trainer instead of failing
    from machine_learning_replications_tpu.parallel import (
        fit_gbdt_sharded, stump_trainer as st,
    )

    old = st.MAX_LAYOUT_BYTES
    st.MAX_LAYOUT_BYTES = 64
    try:
        cfg = GBDTConfig(n_estimators=6, max_depth=1, splitter="hist")
        sh, _ = fit_gbdt_sharded(mesh, X, y, cfg)
    finally:
        st.MAX_LAYOUT_BYTES = old
    ref, _ = gbdt.fit(X, y, cfg)
    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))


def test_mesh_cross_val_matches_single_device(train_data):
    """cross_val_member_probas(mesh=...) routes the GBDT fold fits through
    the sharded trainer; the meta-feature column must match the vmapped
    single-device construction (VERDICT r2 item 5)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from machine_learning_replications_tpu.config import ExperimentConfig, SVCConfig
    from machine_learning_replications_tpu.models import pipeline

    X, y = train_data
    Xs, ys = X[:300], y[:300]
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=10),
        svc=SVCConfig(platt_cv=2, max_iter=500),
    )
    mesh = make_mesh(data=4, model=2)
    meta_mesh = pipeline.cross_val_member_probas(Xs, ys, cfg, mesh=mesh)
    meta_single = pipeline.cross_val_member_probas(Xs, ys, cfg)
    np.testing.assert_allclose(
        meta_mesh[:, 1], meta_single[:, 1], rtol=1e-7, atol=1e-9
    )
    # non-GBDT columns share the single-device path bit for bit
    np.testing.assert_array_equal(meta_mesh[:, 0], meta_single[:, 0])
    np.testing.assert_array_equal(meta_mesh[:, 2], meta_single[:, 2])


def test_sharded_imputer_and_predict_match(cohort):
    """Row-sharded imputer transform and stacked batch prediction equal
    their single-device counterparts (rowwise.apply_rows_sharded)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    import jax.numpy as jnp

    from machine_learning_replications_tpu.models import knn_impute

    X, y, _ = cohort
    mesh = make_mesh(data=4, model=2)
    p = knn_impute.fit(jnp.asarray(X))
    out_mesh = np.asarray(knn_impute.transform(p, jnp.asarray(X), mesh=mesh))
    out_single = np.asarray(knn_impute.transform(p, jnp.asarray(X)))
    np.testing.assert_array_equal(out_mesh, out_single)
    # chunked + sharded path (tail chunk padding + data-axis rounding)
    out_chunked = np.asarray(
        knn_impute.transform(p, jnp.asarray(X), chunk_rows=150, mesh=mesh)
    )
    np.testing.assert_array_equal(out_chunked, out_single)


def test_sharded_exact_high_cardinality(cohort_full):
    """Full-size cohort (1427 unique values in the continuous columns) through
    the sharded trainer under the default exact splitter — pins the uint16
    stump layout; fixtures elsewhere stay under 256 uniques and would miss a
    uint8 regression."""
    import numpy as np

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import gbdt
    from machine_learning_replications_tpu.parallel import make_mesh, stump_trainer

    X, y, _ = cohort_full
    Xs = np.asarray(X[:, selected_indices()])
    assert max(len(np.unique(Xs[:, f])) for f in range(Xs.shape[1])) > 256
    mesh = make_mesh(data=4, model=2)
    cfg = GBDTConfig(n_estimators=8)  # splitter='exact' default
    sharded, _ = stump_trainer.fit(mesh, Xs, y, cfg)
    single, _ = gbdt.fit(Xs, y, cfg)
    np.testing.assert_array_equal(
        np.asarray(sharded.feature), np.asarray(single.feature)
    )
    np.testing.assert_allclose(
        np.asarray(sharded.value), np.asarray(single.value), rtol=1e-5, atol=1e-6
    )


def test_sharded_blocked_boundary_path_equals_single_device(train_data, monkeypatch):
    """Cross-formulation differential: the sharded trainer's per-stage
    histogram+cumsum statistics vs the single-device sorted path in its
    BLOCKED boundary-sum regime (the threshold is lowered so the
    reference takes the block decomposition — since the r5 histogram
    reformulation the sharded side no longer calls
    ``cumulative_boundary_sums`` at all, so this pits the two independent
    implementations of the same sums against each other)."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from machine_learning_replications_tpu.ops import histogram

    monkeypatch.setattr(histogram, "_BLOCKED_BOUNDARY_MIN_N", 16)
    monkeypatch.setattr(histogram, "_BOUNDARY_BLOCK", 32)
    # The thresholds are read at TRACE time inside jitted trainers whose
    # caches key on shapes only — flush before AND after so (a) an earlier
    # same-signature compilation cannot silently bypass the patched values
    # and (b) blocked-path executables don't leak to later parity tests.
    jax.clear_caches()
    try:
        X, y = train_data
        cfg = GBDTConfig(n_estimators=12, max_depth=1)
        ref, _ = gbdt.fit(X, y, cfg)
        mesh = make_mesh(data=4, model=2)
        sh, _ = stump_trainer.fit(mesh, X, y, cfg)
        np.testing.assert_array_equal(
            np.asarray(sh.feature), np.asarray(ref.feature)
        )
        np.testing.assert_allclose(
            np.asarray(sh.threshold), np.asarray(ref.threshold), rtol=1e-12
        )
        np.testing.assert_allclose(
            np.asarray(sh.value), np.asarray(ref.value), rtol=1e-7, atol=1e-10
        )
    finally:
        jax.clear_caches()


def test_nonbinary_labels_use_gather_fallback(train_data):
    """Soft (non-0/1) labels are well-defined under binomial deviance
    (g = y − p) and the sharded trainer consumes labels directly (the
    r5 histogram formulation removed the packed-bins-column fast path
    this test originally guarded); parity vs the single-device fit must
    hold for them too."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    y_soft = np.where(y > 0.5, 0.9, 0.1)
    cfg = GBDTConfig(n_estimators=10, max_depth=1)
    ref, _ = gbdt.fit(X, y_soft, cfg)
    sh, _ = stump_trainer.fit(make_mesh(data=4, model=2), X, y_soft, cfg)
    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))
    np.testing.assert_allclose(
        np.asarray(sh.value), np.asarray(ref.value), rtol=1e-6, atol=1e-9
    )


def test_sharded_blocked_weighted_path_equals_subset(train_data, monkeypatch):
    """WEIGHTED-loop coverage of the same cross-formulation differential
    (the unweighted test above leaves the weighted histogram sums — CL
    hoisting via the weight histogram, zero-weight padding rows —
    unexercised; the blocked threshold patch applies to the single-device
    reference side only). Must still equal the single-device fit on the
    physical subset."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from machine_learning_replications_tpu.ops import binning, histogram

    monkeypatch.setattr(histogram, "_BLOCKED_BOUNDARY_MIN_N", 16)
    monkeypatch.setattr(histogram, "_BOUNDARY_BLOCK", 32)
    jax.clear_caches()
    try:
        X, y = train_data
        X, y = X[:699], y[:699]  # odd size: intra-block padding on shards
        w = (np.arange(X.shape[0]) % 4 != 0).astype(float)
        cfg = GBDTConfig(n_estimators=10, max_depth=1, splitter="hist")
        bins = binning.bin_features(X, 256)
        mesh = make_mesh(data=4, model=2)
        sh, _ = stump_trainer.fit(mesh, X, y, cfg, bins=bins, sample_weight=w)
        sub_bins = binning.BinnedFeatures(
            binned=bins.binned[w > 0], thresholds=bins.thresholds,
            n_bins=bins.n_bins,
        )
        ref, _ = gbdt.fit(X[w > 0], y[w > 0], cfg, bins=sub_bins)
        np.testing.assert_array_equal(
            np.asarray(sh.feature), np.asarray(ref.feature)
        )
        np.testing.assert_allclose(
            np.asarray(sh.value), np.asarray(ref.value), rtol=1e-6, atol=1e-9
        )
    finally:
        jax.clear_caches()


def test_mesh_cross_val_per_fold_binning_matches_single_device(train_data):
    """cfg.gbdt.per_fold_binning must be honored by the mesh fold loop too:
    mesh and single-device runs of the identical per-fold-binning config
    must produce the same GBDT meta-feature column."""
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    from machine_learning_replications_tpu.config import ExperimentConfig, SVCConfig
    from machine_learning_replications_tpu.models import pipeline

    X, y = train_data
    Xs, ys = X[:300], y[:300]
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=8, per_fold_binning=True),
        svc=SVCConfig(platt_cv=2, max_iter=300),
    )
    mesh = make_mesh(data=4, model=2)
    meta_mesh = pipeline.cross_val_member_probas(Xs, ys, cfg, mesh=mesh)
    meta_single = pipeline.cross_val_member_probas(Xs, ys, cfg)
    np.testing.assert_allclose(
        meta_mesh[:, 1], meta_single[:, 1], rtol=1e-7, atol=1e-9
    )


def test_mesh_sweep_matches_single_device(train_data):
    """cv_sweep(mesh=...) — each (depth, fold) fit row-sharded with the
    fold mask riding the trainers' weight path — must reproduce the
    single-device vmapped sweep's AUC surface (the sharded and vmapped
    trainers are independently parity-tested; this checks the sweep-level
    composition end to end)."""
    from machine_learning_replications_tpu.config import SweepConfig
    from machine_learning_replications_tpu.models import sweep

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    cfg = SweepConfig(
        n_estimators_grid=(5, 12), max_depth_grid=(1, 2), cv_folds=3
    )
    single = sweep.cv_sweep(X, y, cfg)
    mesh = make_mesh(data=4, model=2)
    sharded = sweep.cv_sweep(X, y, cfg, mesh=mesh)
    np.testing.assert_allclose(
        sharded.fold_auc, single.fold_auc, rtol=0, atol=1e-9
    )
    assert sharded.best_max_depth == single.best_max_depth
    assert sharded.best_n_estimators == single.best_n_estimators
