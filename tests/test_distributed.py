"""Multi-chip tests on the 8-device virtual CPU mesh (SURVEY.md §4).

The sharded stump trainer must produce the *same forest* as the
single-device trainer — communication (psum of histogram partials,
all_gather of per-shard split bests) must be semantically invisible.
"""

import numpy as np
import pytest

import jax

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.models import gbdt, tree
from machine_learning_replications_tpu.parallel import make_mesh, stump_trainer


@pytest.fixture(scope="module")
def train_data():
    rng = np.random.default_rng(13)
    n, f = 700, 17
    X = rng.normal(size=(n, f))
    X[:, :12] = (X[:, :12] > 0.4).astype(float)
    X[:, 12:] = np.round(X[:, 12:] * 6) / 3
    w = rng.normal(size=f)
    y = (X @ w + 0.8 * rng.normal(size=n) > 0.3).astype(float)
    return X, y


@pytest.mark.parametrize("data,model", [(8, 1), (4, 2), (2, 4), (1, 1)])
def test_sharded_equals_single_device(train_data, data, model):
    if len(jax.devices()) < data * model:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    cfg = GBDTConfig(n_estimators=30, max_depth=1)
    ref, aux_ref = gbdt.fit(X, y, cfg)
    mesh = make_mesh(data=data, model=model)
    sh, aux_sh = stump_trainer.fit(mesh, X, y, cfg)

    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))
    np.testing.assert_allclose(
        np.asarray(sh.threshold), np.asarray(ref.threshold), rtol=1e-12
    )
    np.testing.assert_allclose(
        np.asarray(sh.value), np.asarray(ref.value), rtol=1e-9, atol=1e-12
    )
    np.testing.assert_allclose(
        aux_sh["train_deviance"], aux_ref["train_deviance"], rtol=1e-9
    )


def test_sharded_matches_sklearn(train_data):
    from sklearn.ensemble import GradientBoostingClassifier

    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    sk = GradientBoostingClassifier(n_estimators=25, max_depth=1, random_state=2020).fit(X, y)
    mesh = make_mesh(data=4, model=2)
    params, _ = stump_trainer.fit(mesh, X, y, GBDTConfig(n_estimators=25, max_depth=1))
    np.testing.assert_allclose(
        np.asarray(tree.raw_score(params, X[:100])),
        sk.decision_function(X[:100]),
        rtol=1e-9,
    )


def _assert_buffers_replicated(mesh, X, y, cfg):
    """Every device must hold bit-identical replicas of each output — the
    P() out_spec's claim, which padded model shards once silently violated."""
    for arr in stump_trainer._fit_raw(mesh, X, y, cfg):
        shards = list(arr.addressable_shards)
        ref = np.asarray(shards[0].data)
        for s in shards[1:]:
            np.testing.assert_array_equal(np.asarray(s.data), ref)


@pytest.mark.parametrize("data,model", [(2, 4), (1, 8)])
def test_padded_model_shards_replicated(train_data, data, model):
    # F=5 on model=4 → F_pad=8, shard 3 owns only padded sort slots; on
    # model=8 → shards 5..7 fully padded. Outputs must still be replicated
    # and equal to the single-device forest.
    if len(jax.devices()) < data * model:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    X5 = X[:, :5]
    cfg = GBDTConfig(n_estimators=12, max_depth=1)
    ref, aux_ref = gbdt.fit(X5, y, cfg)
    mesh = make_mesh(data=data, model=model)
    sh, aux = stump_trainer.fit(mesh, X5, y, cfg)
    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))
    np.testing.assert_allclose(np.asarray(sh.value), np.asarray(ref.value), rtol=1e-9)
    np.testing.assert_allclose(
        aux["train_deviance"], aux_ref["train_deviance"], rtol=1e-9
    )
    _assert_buffers_replicated(mesh, X5, y, cfg)


def test_full_mesh_buffers_replicated(train_data):
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X, y = train_data
    mesh = make_mesh(data=4, model=2)
    _assert_buffers_replicated(mesh, X, y, GBDTConfig(n_estimators=10, max_depth=1))


def test_uneven_rows_padding(train_data):
    # 697 rows over 8 shards → 88-row shards, 7 fabricated padding rows
    X, y = train_data
    if len(jax.devices()) < 8:
        pytest.skip("needs 8 virtual devices")
    X697, y697 = X[:697], y[:697]
    cfg = GBDTConfig(n_estimators=10, max_depth=1)
    ref, _ = gbdt.fit(X697, y697, cfg)
    mesh = make_mesh(data=8, model=1)
    sh, _ = stump_trainer.fit(mesh, X697, y697, cfg)
    np.testing.assert_array_equal(np.asarray(sh.feature), np.asarray(ref.feature))
    np.testing.assert_allclose(np.asarray(sh.value), np.asarray(ref.value), rtol=1e-9)


def test_sharded_exact_high_cardinality(cohort_full):
    """Full-size cohort (1427 unique values in the continuous columns) through
    the sharded trainer under the default exact splitter — pins the uint16
    stump layout; fixtures elsewhere stay under 256 uniques and would miss a
    uint8 regression."""
    import numpy as np

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import gbdt
    from machine_learning_replications_tpu.parallel import make_mesh, stump_trainer

    X, y, _ = cohort_full
    Xs = np.asarray(X[:, selected_indices()])
    assert max(len(np.unique(Xs[:, f])) for f in range(Xs.shape[1])) > 256
    mesh = make_mesh(data=4, model=2)
    cfg = GBDTConfig(n_estimators=8)  # splitter='exact' default
    sharded, _ = stump_trainer.fit(mesh, Xs, y, cfg)
    single, _ = gbdt.fit(Xs, y, cfg)
    np.testing.assert_array_equal(
        np.asarray(sharded.feature), np.asarray(single.feature)
    )
    np.testing.assert_allclose(
        np.asarray(sharded.value), np.asarray(single.value), rtol=1e-5, atol=1e-6
    )
