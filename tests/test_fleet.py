"""Fleet tier (fleet/): registry rotation, router retry/hedging,
versioned checkpoints, replica warm swaps, and the rolling-deploy E2E.

The acceptance contract (ISSUE 9): N replicas behind one router with
probe-driven rotation; per-request retry/hedging honoring Retry-After
and the request deadline; monotonic checkpoint version ids; a rolling
deploy that swaps versions with zero failed requests and zero wrong
answers, with the last-known-good rollback as the safety net. Router
mechanics are tested over stub replicas (the fleet tier is jax-free by
design, so stubs keep these tests at HTTP speed); the deploy path runs
against real engines.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

from machine_learning_replications_tpu.fleet import (
    ReplicaRegistry,
    make_router,
    probe_replica,
    rolling_deploy,
)
from machine_learning_replications_tpu.fleet.registry import (
    FLEET_ROTATIONS,
)
from machine_learning_replications_tpu.fleet.router import (
    FLEET_HEDGE_WINS,
    FLEET_HEDGES,
    FLEET_RETRIES,
)
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
)


# ---------------------------------------------------------------------------
# stub replicas: the fleet tier is jax-free, so router mechanics are
# tested against programmable HTTP stubs on the real transport
# ---------------------------------------------------------------------------


class _StubReplica:
    """A programmable replica: flip ``ready``/``mode``/``version`` to
    drive the router through its branches. ``mode``: ok | shed | error
    | stall."""

    def __init__(self, rid: str, version: int = 1) -> None:
        self.rid = rid
        self.version = version
        self.ready = True
        self.mode = "ok"
        self.stall_s = 2.0
        self.served = 0
        self.deadline_headers: list[str | None] = []
        # /admin/deploy behavior (the batched-rollout test): hold the
        # "warm swap" for deploy_s, then serve deploy_to.
        self.deploy_s = 0.0
        self.deploy_to = 2

    def handle_request(self, req, rsp) -> None:
        if req.path == "/readyz":
            rsp.send_json(
                200 if self.ready else 503,
                {"ready": self.ready, "reasons": [],
                 "replica": self.rid, "version": self.version},
            )
            return
        if req.path == "/admin/deploy":
            if self.deploy_s:
                time.sleep(self.deploy_s)
            self.version = self.deploy_to
            rsp.send_json(200, {"deploy": {
                "version": self.version, "rolled_back": False,
                "seconds": self.deploy_s,
            }})
            return
        if req.path != "/predict":
            rsp.send_json(404, {"error": "nope"})
            return
        self.deadline_headers.append(
            req.get_header("x-request-deadline-ms")
        )
        if self.mode == "shed":
            rsp.send_json(
                503, {"error": "overloaded"},
                headers={"Retry-After": "1"},
            )
            return
        if self.mode == "error":
            rsp.send_json(500, {"error": "boom"})
            return
        if self.mode == "stall":
            time.sleep(self.stall_s)
        self.served += 1
        rsp.send_json(
            200, {"probability": 0.25, "text": "x"},
            headers={
                "X-Replica": self.rid,
                "X-Model-Version": str(self.version),
                "X-Serve-Path": "host",
            },
            request_id=req.get_header("x-request-id"),
        )

    def handle_protocol_error(self, exc, rsp) -> None:
        rsp.send_json(exc.code, {"error": exc.message}, close=True)


def _start_stub(app):
    httpd = EventLoopHttpServer(("127.0.0.1", 0), app)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _stub_fleet(n=2, **router_kw):
    """n stub replicas behind a live router; returns
    (router, stubs, stub_httpds, base_url)."""
    stubs, httpds, members = [], [], []
    for i in range(n):
        stub = _StubReplica(f"r{i + 1}")
        httpd, url = _start_stub(stub)
        stubs.append(stub)
        httpds.append(httpd)
        members.append((stub.rid, url))
    kw = dict(
        port=0, replicas=members, probe_interval_s=0.1,
        request_timeout_s=5.0,
    )
    kw.update(router_kw)
    router = make_router(**kw).start_background()
    deadline = time.monotonic() + 10
    while router.registry.ready_count() < n and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert router.registry.ready_count() == n, router.registry.snapshot()
    return router, stubs, httpds, f"http://{router.address[0]}:{router.address[1]}"


def _teardown(router, httpds):
    router.shutdown()
    for h in httpds:
        h.server_close()


def _post_predict(base, timeout=10.0, **headers):
    req = urllib.request.Request(
        base + "/predict", data=b'{"x": 1}',
        headers={"Content-Type": "application/json", **headers},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, dict(resp.headers), json.loads(resp.read())


# ---------------------------------------------------------------------------
# registry state machine (pure — no sockets)
# ---------------------------------------------------------------------------


def test_registry_probe_rotation_state_machine():
    reg = ReplicaRegistry(fail_threshold=2, recover_probes=2)
    reg.register("a", "http://x:1")
    assert reg.get("a")["state"] == "probing"
    assert reg.pick() is None  # never-probed replicas get no traffic
    # First ready probe rotates in.
    reg.observe_probe("a", ok=True, ready=True, version=3)
    rep = reg.get("a")
    assert rep["state"] == "ready" and rep["in_rotation"]
    assert rep["version"] == 3
    # One dropped probe is NOT enough to rotate out...
    reg.observe_probe("a", ok=False, ready=False)
    assert reg.get("a")["in_rotation"]
    # ...fail_threshold consecutive ones are.
    reg.observe_probe("a", ok=False, ready=False)
    assert reg.get("a")["state"] == "out"
    # Recovery needs recover_probes CONSECUTIVE ready probes.
    reg.observe_probe("a", ok=True, ready=True)
    assert reg.get("a")["state"] == "out"
    reg.observe_probe("a", ok=True, ready=True)
    assert reg.get("a")["in_rotation"]
    # An explicit not-ready (the replica said so) rotates out on the
    # FIRST probe.
    reg.observe_probe("a", ok=True, ready=False)
    assert reg.get("a")["state"] == "out"


def test_registry_breaker_and_admin_hold():
    reg = ReplicaRegistry(breaker_failures=2, recover_probes=1)
    reg.register("a", "http://x:1")
    reg.observe_probe("a", ok=True, ready=True)
    reg.mark_failure("a", "conn reset")
    assert reg.get("a")["in_rotation"]  # one strike is not an outage
    reg.mark_success("a")
    reg.mark_failure("a", "conn reset")
    assert reg.get("a")["in_rotation"]  # success reset the streak
    reg.mark_failure("a", "conn reset")
    reg.mark_failure("a", "conn reset")
    assert reg.get("a")["state"] == "out"  # breaker open
    reg.observe_probe("a", ok=True, ready=True)
    assert reg.get("a")["in_rotation"]
    # Admin hold is orthogonal to probe state.
    assert reg.hold("a")
    assert not reg.get("a")["in_rotation"]
    assert reg.get("a")["state"] == "ready"  # probes unaffected
    assert reg.pick() is None
    assert reg.release("a")
    assert reg.get("a")["in_rotation"]


def test_registry_breaker_recovery_honors_hysteresis():
    # probe_oks accumulated while READY must not count toward the
    # post-outage recovery gate: a breaker-opened replica re-enters only
    # after recover_probes CONSECUTIVE ready probes from the transition.
    reg = ReplicaRegistry(recover_probes=3, breaker_failures=2)
    reg.register("a", "http://x:1")
    for _ in range(5):
        reg.observe_probe("a", ok=True, ready=True)
    reg.mark_failure("a", "conn reset")
    reg.mark_failure("a", "conn reset")
    assert reg.get("a")["state"] == "out"  # breaker open
    reg.observe_probe("a", ok=True, ready=True)
    assert reg.get("a")["state"] == "out"  # 1 of 3
    reg.observe_probe("a", ok=True, ready=True)
    assert reg.get("a")["state"] == "out"  # 2 of 3
    reg.observe_probe("a", ok=True, ready=True)
    assert reg.get("a")["in_rotation"]


def test_registry_replacement_accounts_rotation_out():
    # Re-registering an id with a NEW url (respawn on another port)
    # replaces an in-rotation replica with a PROBING one — capacity
    # left rotation, so the books must say so like deregister's do.
    reg = ReplicaRegistry()
    reg.register("a", "http://x:1")
    reg.observe_probe("a", ok=True, ready=True)
    out0 = FLEET_ROTATIONS.labels(direction="out").value
    reg.register("a", "http://x:2")
    assert reg.get("a")["state"] == "probing"
    assert reg.get("a")["url"] == "http://x:2"
    assert FLEET_ROTATIONS.labels(direction="out").value == out0 + 1


def test_registry_pick_spreads_cold_fleet_and_exclude():
    # With no load signal yet, power-of-two-choices ties break to the
    # least recently picked of each sampled pair, so a cold fleet still
    # spreads traffic across every replica.
    reg = ReplicaRegistry()
    for rid in ("a", "b", "c"):
        reg.register(rid, f"http://{rid}:1")
        reg.observe_probe(rid, ok=True, ready=True)
    picks = [reg.pick()["id"] for _ in range(64)]
    assert sorted(set(picks)) == ["a", "b", "c"]
    counts = {rid: picks.count(rid) for rid in ("a", "b", "c")}
    assert all(n >= 8 for n in counts.values()), counts
    # exclude prefers untried replicas...
    assert reg.pick(exclude={"a", "b"})["id"] == "c"
    # ...but falls back to a tried one rather than failing the request.
    assert reg.pick(exclude={"a", "b", "c"}) is not None
    # Re-registration with the same url is idempotent (keeps state).
    reg.register("a", "http://a:1")
    assert reg.get("a")["state"] == "ready"
    # Deregistration removes from rotation.
    assert reg.deregister("b")
    assert all(reg.pick()["id"] != "b" for _ in range(6))


def test_checkpoint_version_monotonic(tmp_path):
    import jax.numpy as jnp

    from machine_learning_replications_tpu.models.scaler import ScalerParams
    from machine_learning_replications_tpu.persist import orbax_io
    from machine_learning_replications_tpu.resilience import lastgood

    ckpt = str(tmp_path / "m")
    p1 = ScalerParams(mean=jnp.zeros(3), scale=jnp.ones(3))
    p2 = ScalerParams(mean=jnp.ones(3), scale=jnp.ones(3))
    orbax_io.save_model(ckpt, p1)
    assert orbax_io.checkpoint_version(ckpt) == 1
    orbax_io.save_model(ckpt, p2)
    assert orbax_io.checkpoint_version(ckpt) == 2
    # The previous version is retained — WITH its id.
    assert orbax_io.checkpoint_version(lastgood.lastgood_path(ckpt)) == 1
    params, info = orbax_io.load_model_versioned(ckpt)
    assert info["version"] == 2 and not info["rolled_back"]
    # The counter never moves backwards across the publish rotation.
    orbax_io.save_model(ckpt, p1)
    assert orbax_io.checkpoint_version(ckpt) == 3


def test_load_model_versioned_reports_rollback(tmp_path):
    import jax.numpy as jnp

    from machine_learning_replications_tpu.models.scaler import ScalerParams
    from machine_learning_replications_tpu.persist import orbax_io
    from machine_learning_replications_tpu.resilience import faults

    ckpt = str(tmp_path / "m")
    orbax_io.save_model(
        ckpt, ScalerParams(mean=jnp.zeros(3), scale=jnp.ones(3))
    )
    orbax_io.save_model(
        ckpt, ScalerParams(mean=jnp.ones(3), scale=jnp.ones(3))
    )
    faults.arm("persist.restore:corrupt@once")
    try:
        params, info = orbax_io.load_model_versioned(ckpt)
    finally:
        faults.reset()
    # The corrupt primary (v2) rolled back to the retained v1 — and the
    # info says so: a deploy must not report the target as shipped.
    assert info["rolled_back"] and info["version"] == 1
    assert float(np.asarray(params.mean)[0]) == 0.0


# ---------------------------------------------------------------------------
# router data path over stub replicas
# ---------------------------------------------------------------------------


def test_router_least_loaded_rotation_and_identity_passthrough():
    # Least-loaded picking must still EXPLORE: an unsampled replica is
    # preferred until it has a latency measurement, so both replicas see
    # traffic even from a strictly sequential client (a concentration on
    # the faster replica afterwards is the new contract, not a bug —
    # the load-spreading behavior under concurrency is asserted in
    # test_registry_least_loaded_*).
    router, stubs, httpds, base = _stub_fleet(2)
    try:
        stubs[1].version = 2
        seen = set()
        for _ in range(8):
            code, headers, body = _post_predict(base)
            assert code == 200 and body["probability"] == 0.25
            seen.add((headers["X-Replica"], headers["X-Model-Version"]))
            assert headers["X-Serve-Path"] == "host"
            assert "X-Request-Id" in headers
        assert seen == {("r1", "1"), ("r2", "2")}
        assert stubs[0].served >= 1 and stubs[1].served >= 1
        # The remaining deadline rode down to the replicas.
        raw = [h for s in stubs for h in s.deadline_headers if h]
        assert raw and all(0 < float(h) <= 5000 for h in raw)
    finally:
        _teardown(router, httpds)


def test_router_retries_dead_replica_and_breaker_rotates_out():
    router, stubs, httpds, base = _stub_fleet(2)
    retries0 = FLEET_RETRIES.labels(reason="conn_error").value
    try:
        httpds[0].server_close()  # r1 dies
        for _ in range(6):
            code, headers, _ = _post_predict(base)
            assert code == 200
            assert headers["X-Replica"] == "r2"
        assert FLEET_RETRIES.labels(reason="conn_error").value > retries0
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if (router.registry.get("r1") or {}).get("state") == "out":
                break
            time.sleep(0.05)
        assert router.registry.get("r1")["state"] == "out"
    finally:
        _teardown(router, httpds[1:])


def test_router_shed_retries_elsewhere_then_passes_through():
    router, stubs, httpds, base = _stub_fleet(2)
    try:
        # One shedding replica: the other absorbs every request.
        stubs[0].mode = "shed"
        for _ in range(6):
            code, headers, _ = _post_predict(base)
            assert code == 200 and headers["X-Replica"] == "r2"
        # Whole fleet shedding: the 503 + Retry-After passes through
        # (the router cannot conjure capacity).
        stubs[1].mode = "shed"
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _post_predict(base, timeout=8.0)
        assert exc_info.value.code == 503
        assert exc_info.value.headers.get("Retry-After")
        exc_info.value.read()
    finally:
        _teardown(router, httpds)


def test_router_deadline_504_never_hangs():
    router, stubs, httpds, base = _stub_fleet(
        1, request_timeout_s=0.5, hedge_ms=0.0, fail_threshold=50,
    )
    try:
        stubs[0].mode = "stall"
        stubs[0].stall_s = 3.0
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _post_predict(base, timeout=8.0)
        dt = time.monotonic() - t0
        assert exc_info.value.code == 504
        exc_info.value.read()
        # Bounded by the router deadline, not the replica's stall.
        assert dt < 2.5, dt
    finally:
        _teardown(router, httpds)


def test_router_client_deadline_header_tightens():
    router, stubs, httpds, base = _stub_fleet(
        1, request_timeout_s=30.0, hedge_ms=0.0, fail_threshold=50,
    )
    try:
        stubs[0].mode = "stall"
        stubs[0].stall_s = 3.0
        t0 = time.monotonic()
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _post_predict(
                base, timeout=8.0, **{"X-Request-Deadline-Ms": "400"}
            )
        assert exc_info.value.code == 504
        exc_info.value.read()
        assert time.monotonic() - t0 < 2.5
    finally:
        _teardown(router, httpds)


def test_router_hedges_around_a_stalled_replica():
    router, stubs, httpds, base = _stub_fleet(
        2, hedge_ms=100.0, request_timeout_s=8.0, fail_threshold=50,
    )
    hedges0 = FLEET_HEDGES.get().value
    wins0 = FLEET_HEDGE_WINS.get().value
    try:
        stubs[0].mode = "stall"
        stubs[0].stall_s = 1.5
        # Two sequential requests: round-robin lands one of them on the
        # stalled replica, whose hedge fires to the fast one.
        for _ in range(2):
            t0 = time.monotonic()
            code, headers, _ = _post_predict(base)
            assert code == 200
            assert time.monotonic() - t0 < 1.2  # never the full stall
        assert FLEET_HEDGES.get().value > hedges0
        assert FLEET_HEDGE_WINS.get().value > wins0
    finally:
        _teardown(router, httpds)


def test_router_never_hedges_to_the_replica_already_tried():
    # One in-rotation replica, stalled: pick(exclude) falls back to the
    # already-tried replica, and hedging it with a duplicate to ITSELF
    # would double the load on the one struggling server — no hedge.
    router, stubs, httpds, base = _stub_fleet(
        1, hedge_ms=50.0, request_timeout_s=8.0, fail_threshold=50,
    )
    hedges0 = FLEET_HEDGES.get().value
    try:
        stubs[0].mode = "stall"
        stubs[0].stall_s = 1.0
        code, headers, _ = _post_predict(base)
        assert code == 200 and headers["X-Replica"] == "r1"
        assert stubs[0].served == 1  # no duplicate arrived
        assert FLEET_HEDGES.get().value == hedges0
    finally:
        _teardown(router, httpds)


def test_router_hedge_counts_against_max_attempts():
    # --max-attempts is the per-request upstream budget, hedges
    # included: with the cap already spent, the hedge timer must not
    # fire a second attempt.
    router, stubs, httpds, base = _stub_fleet(
        2, hedge_ms=50.0, request_timeout_s=8.0, fail_threshold=50,
        max_attempts=1,
    )
    hedges0 = FLEET_HEDGES.get().value
    try:
        stubs[0].mode = "stall"
        stubs[0].stall_s = 1.0
        # Round-robin lands one of these on the stalled replica, whose
        # hedge timer expires — and must stay silent.
        for _ in range(2):
            code, _, _ = _post_predict(base)
            assert code == 200
        assert FLEET_HEDGES.get().value == hedges0
    finally:
        _teardown(router, httpds)


def test_fleet_deploy_cli_409_is_a_refusal_not_success(monkeypatch):
    # The 409 body carries the OTHER rollout's live status (result "ok"
    # from its first publish) — the CLI must refuse, not print success
    # for a deploy that never started.
    import io

    from machine_learning_replications_tpu.cli import _run_fleet_deploy

    def fake_urlopen(req, timeout=None):
        raise urllib.error.HTTPError(
            req.full_url, 409, "conflict", {},
            io.BytesIO(json.dumps({
                "error": "a rolling deploy is already in progress",
                "deploy": {"result": "ok", "state": "warming"},
            }).encode()),
        )

    monkeypatch.setattr(urllib.request, "urlopen", fake_urlopen)
    import argparse

    args = argparse.Namespace(router="http://r", model="/m", timeout=5)
    with pytest.raises(SystemExit) as exc_info:
        _run_fleet_deploy(args)
    assert "already in progress" in str(exc_info.value)


def test_router_no_ready_replicas_is_an_explicit_503():
    router = make_router(port=0, probe_interval_s=0.1).start_background()
    base = f"http://{router.address[0]}:{router.address[1]}"
    try:
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _post_predict(base)
        assert exc_info.value.code == 503
        assert exc_info.value.headers.get("Retry-After") == "1"
        exc_info.value.read()
        # /readyz says why.
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(base + "/readyz", timeout=5)
        assert exc_info.value.code == 503
        body = json.loads(exc_info.value.read())
        assert body["reasons"] == ["no ready replicas"]
    finally:
        router.shutdown()


def test_router_4xx_passes_through_without_retry():
    router, stubs, httpds, base = _stub_fleet(2)
    try:
        # The stub 404s any non-predict path; a predict-level 4xx needs
        # a custom mode — reuse "error"→500 for retry and check 400 via
        # a direct stub tweak.
        stubs[0].mode = stubs[1].mode = "bad"

        def handle(req, rsp, _orig=_StubReplica.handle_request):
            rsp.send_json(400, {"error": "bad patient"})

        served0 = stubs[0].served + stubs[1].served
        stubs[0].handle_request = handle
        stubs[1].handle_request = handle
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            _post_predict(base)
        assert exc_info.value.code == 400
        exc_info.value.read()
        assert stubs[0].served + stubs[1].served == served0
    finally:
        _teardown(router, httpds)


def test_router_http_registration_and_deregistration():
    router = make_router(port=0, probe_interval_s=0.1).start_background()
    base = f"http://{router.address[0]}:{router.address[1]}"
    stub = _StubReplica("dyn")
    httpd, url = _start_stub(stub)
    try:
        req = urllib.request.Request(
            base + "/fleet/replicas",
            data=json.dumps({"id": "dyn", "url": url}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.loads(resp.read())["replica"]["id"] == "dyn"
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline and \
                router.registry.ready_count() < 1:
            time.sleep(0.02)
        code, headers, _ = _post_predict(base)
        assert code == 200 and headers["X-Replica"] == "dyn"
        req = urllib.request.Request(
            base + "/fleet/replicas",
            data=json.dumps({"deregister": "dyn"}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=5) as resp:
            assert json.loads(resp.read())["deregistered"]
        assert router.registry.ready_count() == 0
    finally:
        router.shutdown()
        httpd.server_close()


def test_router_metrics_strict_and_debug_requests():
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from validate_metrics import validate

    router, stubs, httpds, base = _stub_fleet(2)
    try:
        for _ in range(4):
            _post_predict(base)
        with urllib.request.urlopen(base + "/metrics", timeout=5) as resp:
            page = resp.read().decode()
        assert not validate(page), validate(page)[:5]
        for family in ("fleet_requests_total", "fleet_replicas",
                       "fleet_request_latency_seconds",
                       "fleet_probe_total"):
            assert family in page
        with urllib.request.urlopen(
            base + "/debug/requests", timeout=5
        ) as resp:
            dbg = json.loads(resp.read())
        assert dbg["stats"]["kept_total"] >= 1
        trace = dbg["requests"][0]
        assert "upstream" in trace["phases"]
        assert trace["replica"] in ("r1", "r2")
    finally:
        _teardown(router, httpds)


def test_probe_replica_verdicts():
    stub = _StubReplica("p", version=7)
    httpd, url = _start_stub(stub)
    try:
        v = probe_replica(url)
        # NTP-style clock sampling (obs.fleettrace): the prober stamps
        # t_send/t_recv around the probe; clock_perf is None unless the
        # replica echoes its perf_counter on /readyz (the stub doesn't).
        assert v["t_send"] <= v["t_recv"]
        assert v["clock_perf"] is None
        assert {k: v[k] for k in ("ok", "ready", "version", "queue_depth")} \
            == {"ok": True, "ready": True, "version": 7, "queue_depth": None}
        stub.ready = False
        v = probe_replica(url)
        assert v["ok"] and not v["ready"]
    finally:
        httpd.server_close()
    v = probe_replica(url)  # dead server
    assert not v["ok"] and not v["ready"]


def test_loadgen_fleet_block_records_replica_version_split(tmp_path):
    import subprocess
    import sys

    router, stubs, httpds, base = _stub_fleet(2)
    try:
        stubs[1].version = 2
        out = subprocess.run(
            [sys.executable,
             os.path.join(os.path.dirname(__file__), "..", "tools",
                          "loadgen.py"),
             "--url", base, "--mode", "closed", "--concurrency", "2",
             "--duration", "1",
             "--out", str(tmp_path / "art.json")],
            capture_output=True, text=True, check=True,
        )
        art = json.loads(out.stdout)
        fleet = art["fleet"]
        assert set(fleet["replicas"]) == {"r1", "r2"}
        assert set(fleet["versions"]) == {"1", "2"}
        for v in fleet["versions"].values():
            assert v["n"] > 0 and v["last_s"] >= v["first_s"] >= 0
        assert fleet["by_replica_version"]["r2"] == {"2": fleet["replicas"]["r2"]}
    finally:
        _teardown(router, httpds)


def test_obs_report_fleet_section(tmp_path):
    import subprocess
    import sys

    journal_path = tmp_path / "router.jsonl"
    events = [
        {"kind": "manifest", "run_id": "x", "ts": "t", "command": "fleet"},
        {"ts": "t1", "kind": "fleet_replica_registered", "replica": "r1",
         "url": "http://x:1"},
        {"ts": "t2", "kind": "fleet_rotation", "replica": "r1",
         "direction": "in", "reason": "ready probe", "version": 1},
        {"ts": "t3", "kind": "fleet_deploy_start", "model": "m",
         "target_version": 2, "replicas": ["r1"]},
        {"ts": "t4", "kind": "fleet_deploy_replica", "model": "m",
         "replica": "r1", "result": "ok", "achieved_version": 2},
        {"ts": "t5", "kind": "fleet_deploy_done", "model": "m",
         "result": "ok", "target_version": 2},
    ]
    journal_path.write_text(
        "".join(json.dumps(e) + "\n" for e in events)
    )
    metrics_path = tmp_path / "metrics.json"
    metrics_path.write_text(json.dumps({
        "runtime": {
            "fleet_requests_total": {"outcome=ok": 10},
            "fleet_request_latency_seconds": {"sum": 0.05, "count": 10},
        },
        "replicas": [{"id": "r1", "state": "ready", "in_rotation": True,
                      "version": 2, "url": "http://x:1"}],
    }))
    out = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "obs_report.py"),
         "--fleet", "--journal", str(journal_path),
         "--metrics", str(metrics_path)],
        capture_output=True, text=True, check=True,
    )
    assert "## Fleet" in out.stdout
    assert "r1" in out.stdout and "ok=10" in out.stdout
    assert "deploy arc" in out.stdout and "version 2" in out.stdout


def test_rolling_deploy_batched_holds_respect_capacity_gate():
    """ISSUE 11 satellite: a 4-replica rollout with concurrency 3 —
    warm swaps overlap (observed ≥ 2 concurrent holds) and the number
    of in-rotation replicas never drops below the gate, sampled
    continuously through the rollout."""
    router, stubs, httpds, base = _stub_fleet(4, probe_interval_s=0.05)
    try:
        for s in stubs:
            s.deploy_s = 0.4
            s.deploy_to = 2
        floor_violations: list = []
        max_held = [0]
        stop = threading.Event()

        def sampler():
            while not stop.is_set():
                snap = router.registry.snapshot()
                in_rot = sum(1 for r in snap if r["in_rotation"])
                held = sum(1 for r in snap if r["held"])
                max_held[0] = max(max_held[0], held)
                if in_rot < 1:
                    floor_violations.append(snap)
                time.sleep(0.01)

        sampler_thread = threading.Thread(target=sampler, daemon=True)
        sampler_thread.start()
        report = rolling_deploy(
            router.registry, "/nonexistent-ckpt", concurrency=3,
            admin_timeout_s=30.0, ready_timeout_s=30.0,
        )
        stop.set()
        sampler_thread.join(timeout=5)
        assert report["result"] == "ok", report
        assert report["target_version"] == 2
        assert report["concurrency"] == 3
        assert [s["achieved_version"] for s in report["replicas"]] == \
            [2, 2, 2, 2]
        assert not floor_violations, floor_violations[0]
        # The point of batching: the 0.4 s warm swaps really overlapped.
        assert max_held[0] >= 2, max_held
        snap = router.registry.snapshot()
        assert all(r["version"] == 2 and r["in_rotation"] for r in snap)
    finally:
        _teardown(router, httpds)


def test_rolling_deploy_serial_default_unchanged():
    # concurrency=1 keeps the one-at-a-time contract byte-for-byte.
    router, stubs, httpds, base = _stub_fleet(2, probe_interval_s=0.05)
    try:
        for s in stubs:
            s.deploy_to = 2
        report = rolling_deploy(
            router.registry, "/nonexistent-ckpt",
            admin_timeout_s=30.0, ready_timeout_s=30.0,
        )
        assert report["result"] == "ok"
        assert [s["achieved_version"] for s in report["replicas"]] == [2, 2]
    finally:
        _teardown(router, httpds)


def test_router_hold_release_http_ops():
    """The lifecycle manager's drain-first door: {"hold": id} removes a
    replica from routing over HTTP, {"release": id} puts it back."""
    router, stubs, httpds, base = _stub_fleet(2)
    try:
        def post(body):
            req = urllib.request.Request(
                base + "/fleet/replicas", data=json.dumps(body).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=5) as resp:
                return json.loads(resp.read())

        assert post({"hold": "r1"})["held"] is True
        assert not router.registry.get("r1")["in_rotation"]
        for _ in range(6):
            code, headers, _ = _post_predict(base)
            assert code == 200 and headers["X-Replica"] == "r2"
        assert post({"hold": "r1"})["held"] is False  # already held
        assert post({"release": "r1"})["released"] is True
        assert router.registry.get("r1")["in_rotation"]
        assert post({"release": "ghost"})["released"] is False
    finally:
        _teardown(router, httpds)


# ---------------------------------------------------------------------------
# registry heartbeat/expiry edges (ISSUE 11 satellite)
# ---------------------------------------------------------------------------


def test_registry_probe_expiry_mid_drain_hold():
    """A replica that stops answering mid-drain (held): the OUT
    transition must not double-count the rotation it already left at
    hold time, and release() must NOT put a dead replica back in
    rotation — probes own that door."""
    reg = ReplicaRegistry(fail_threshold=2, recover_probes=2)
    reg.register("a", "http://x:1")
    reg.observe_probe("a", ok=True, ready=True)
    in0 = FLEET_ROTATIONS.labels(direction="in").value
    out0 = FLEET_ROTATIONS.labels(direction="out").value
    assert reg.hold("a")
    assert FLEET_ROTATIONS.labels(direction="out").value == out0 + 1
    # The drain outlives the process: probes start failing while held.
    reg.observe_probe("a", ok=False, ready=False)
    reg.observe_probe("a", ok=False, ready=False)
    assert reg.get("a")["state"] == "out"
    assert FLEET_ROTATIONS.labels(direction="out").value == out0 + 1
    assert reg.release("a")
    assert not reg.get("a")["in_rotation"]
    assert FLEET_ROTATIONS.labels(direction="in").value == in0
    # Recovery is earned through the normal hysteresis, nothing else.
    reg.observe_probe("a", ok=True, ready=True)
    assert not reg.get("a")["in_rotation"]
    reg.observe_probe("a", ok=True, ready=True)
    assert reg.get("a")["in_rotation"]
    assert FLEET_ROTATIONS.labels(direction="in").value == in0 + 1


def test_registry_hold_of_never_ready_replica_counts_no_rotation():
    reg = ReplicaRegistry()
    reg.register("a", "http://x:1")  # probing: never entered rotation
    out0 = FLEET_ROTATIONS.labels(direction="out").value
    assert reg.hold("a")
    assert FLEET_ROTATIONS.labels(direction="out").value == out0


def test_registry_reenrol_same_id_after_crash_keeps_hysteresis():
    """A crashed replica's replacement re-enrols under the same id and
    url (the lifecycle manager's respawn): the idempotent registration
    must keep the OUT state — re-entering rotation is earned through
    recover_probes, never granted by a registration POST."""
    reg = ReplicaRegistry(fail_threshold=2, recover_probes=2)
    reg.register("a", "http://x:1")
    reg.observe_probe("a", ok=True, ready=True)
    reg.observe_probe("a", ok=False, ready=False)
    reg.observe_probe("a", ok=False, ready=False)
    assert reg.get("a")["state"] == "out"
    # The respawned process's registration heartbeat.
    reg.register("a", "http://x:1")
    assert reg.get("a")["state"] == "out"
    assert reg.pick() is None
    reg.observe_probe("a", ok=True, ready=True)
    assert not reg.get("a")["in_rotation"]  # 1 of 2
    reg.observe_probe("a", ok=True, ready=True)
    assert reg.get("a")["in_rotation"]


def test_registry_expiry_races_concurrent_scale_in():
    """Probe expiry racing a concurrent deregistration (the autoscaler's
    scale-in) and hold/release churn: no exceptions, no resurrection of
    the deregistered replica, registry left consistent."""
    reg = ReplicaRegistry(fail_threshold=1)
    for rid in ("a", "b"):
        reg.register(rid, f"http://{rid}:1")
        reg.observe_probe(rid, ok=True, ready=True)
    stop = threading.Event()
    errors: list = []

    def prober():
        while not stop.is_set():
            try:
                reg.observe_probe("a", ok=False, ready=False)
                reg.observe_probe("a", ok=True, ready=True)
                reg.hold("a")
                reg.release("a")
            except Exception as exc:  # pragma: no cover
                errors.append(exc)
                return

    threads = [threading.Thread(target=prober) for _ in range(4)]
    for t in threads:
        t.start()
    time.sleep(0.05)
    assert reg.deregister("a")
    stop.set()
    for t in threads:
        t.join(timeout=5)
    assert not errors
    assert reg.get("a") is None
    assert not reg.deregister("a")
    assert not reg.hold("a") and not reg.release("a")
    reg.observe_probe("a", ok=True, ready=True)  # late expiry: no-op
    assert reg.get("a") is None
    assert [r["id"] for r in reg.snapshot()] == ["b"]
    assert reg.pick()["id"] == "b"


# ---------------------------------------------------------------------------
# real engines: the replica-side warm swap and the rolling-deploy E2E
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def versioned_ckpt(tmp_path_factory):
    """A versioned checkpoint directory holding params v1, plus the v2
    params to publish mid-test, and per-version golden probabilities."""
    from sklearn.ensemble import (
        GradientBoostingClassifier, StackingClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.persist import (
        import_stacking, orbax_io,
    )

    def fit(seed):
        rng = np.random.default_rng(seed)
        X = rng.normal(size=(160, 17))
        y = (X @ rng.normal(size=17) > 0).astype(float)
        with warnings.catch_warnings():
            warnings.simplefilter("ignore")
            clf = StackingClassifier(
                estimators=[
                    ("svc", make_pipeline(
                        StandardScaler(),
                        SVC(probability=True, random_state=0))),
                    ("gbc", GradientBoostingClassifier(
                        n_estimators=5, max_depth=1, random_state=0)),
                    ("lg", LogisticRegression()),
                ],
                final_estimator=LogisticRegression(),
            ).fit(X, y)
        return import_stacking(clf)

    ckpt = str(tmp_path_factory.mktemp("fleet_ckpt") / "model")
    p1, p2 = fit(seed=7), fit(seed=11)
    orbax_io.save_model(ckpt, p1)
    goldens = {
        v: float(np.asarray(stacking.predict_proba1(p, patient_row()))[0])
        for v, p in ((1, p1), (2, p2))
    }
    assert goldens[1] != goldens[2]
    return {"ckpt": ckpt, "p2": p2, "goldens": goldens}


def _real_replica(versioned_ckpt, rid):
    from machine_learning_replications_tpu.persist import orbax_io
    from machine_learning_replications_tpu.serve import make_server

    params, info = orbax_io.load_model_versioned(versioned_ckpt["ckpt"])
    return make_server(
        params, port=0, buckets=(1, 8), max_wait_ms=2.0,
        model_version=info["version"], replica_id=rid,
        admin_endpoint=True,
    ).start_background()


def test_admin_deploy_requires_opt_in(versioned_ckpt):
    from machine_learning_replications_tpu.persist import orbax_io
    from machine_learning_replications_tpu.serve import make_server

    params, info = orbax_io.load_model_versioned(versioned_ckpt["ckpt"])
    handle = make_server(
        params, port=0, buckets=(1,), max_wait_ms=2.0,
        model_version=info["version"],
    ).start_background()
    base = f"http://{handle.address[0]}:{handle.address[1]}"
    try:
        req = urllib.request.Request(
            base + "/admin/deploy",
            data=json.dumps({"model": versioned_ckpt["ckpt"]}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as exc_info:
            urllib.request.urlopen(req, timeout=10)
        assert exc_info.value.code == 403
        exc_info.value.read()
    finally:
        handle.shutdown()


def test_rolling_deploy_e2e_zero_downtime(versioned_ckpt):
    """The acceptance demo, in-process: two replicas behind the router
    under continuous traffic → publish v2 → rolling deploy → zero
    failed requests, zero wrong answers (bit-for-bit vs the per-version
    golden), version crossover observed, both replicas at v2."""
    from machine_learning_replications_tpu.data.examples import (
        EXAMPLE_PATIENT,
    )
    from machine_learning_replications_tpu.persist import orbax_io

    goldens = versioned_ckpt["goldens"]
    replicas = [
        (rid, _real_replica(versioned_ckpt, rid)) for rid in ("r1", "r2")
    ]
    router = make_router(
        port=0,
        replicas=[
            (rid, f"http://{h.address[0]}:{h.address[1]}")
            for rid, h in replicas
        ],
        probe_interval_s=0.2, request_timeout_s=10.0, hedge_ms=300.0,
    ).start_background()
    base = f"http://{router.address[0]}:{router.address[1]}"
    stop = threading.Event()
    outcomes = {"ok": 0, "err": 0, "wrong": 0}
    served_bits = {}  # version -> set of distinct served probabilities
    lock = threading.Lock()

    def traffic():
        body = json.dumps(dict(EXAMPLE_PATIENT)).encode()
        while not stop.is_set():
            try:
                req = urllib.request.Request(
                    base + "/predict", data=body,
                    headers={"Content-Type": "application/json"},
                )
                with urllib.request.urlopen(req, timeout=10) as resp:
                    payload = json.loads(resp.read())
                    version = int(resp.headers["X-Model-Version"])
                prob = payload["probability"]
                # Correct = the eager golden for the reply's version
                # within the engine parity tolerance (jit vs eager
                # fusion noise); versions differ at 1e-1, so a
                # wrong-version answer can never sneak through. Exact
                # bit consistency is asserted separately below: every
                # reply of one version must carry the same bits.
                with lock:
                    served_bits.setdefault(version, set()).add(prob)
                    if abs(prob - goldens[version]) <= 1e-6:
                        outcomes["ok"] += 1
                    else:
                        outcomes["wrong"] += 1
            except Exception:
                with lock:
                    outcomes["err"] += 1
            time.sleep(0.02)

    thread = threading.Thread(target=traffic, daemon=True)
    try:
        deadline = time.monotonic() + 30
        while router.registry.ready_count() < 2 and \
                time.monotonic() < deadline:
            time.sleep(0.05)
        assert router.registry.ready_count() == 2
        thread.start()
        time.sleep(0.5)
        orbax_io.save_model(versioned_ckpt["ckpt"], versioned_ckpt["p2"])
        report = rolling_deploy(
            router.registry, versioned_ckpt["ckpt"],
            admin_timeout_s=300.0,
        )
        assert report["result"] == "ok", report
        assert report["target_version"] == 2
        assert [s["achieved_version"] for s in report["replicas"]] == [2, 2]
        time.sleep(0.5)
        stop.set()
        thread.join(timeout=15)
        assert outcomes["err"] == 0 and outcomes["wrong"] == 0, outcomes
        assert outcomes["ok"] > 0
        assert set(served_bits) == {1, 2}, served_bits
        # Bit-for-bit per version: across replicas, paths, and the
        # deploy crossover, one version serves exactly one bit pattern.
        for version, bits in served_bits.items():
            assert len(bits) == 1, (version, bits)
        snap = router.registry.snapshot()
        assert all(
            r["version"] == 2 and r["in_rotation"] for r in snap
        ), snap
        # The replicas really serve the v2 bits on both scoring paths.
        for _rid, handle in replicas:
            assert handle.model_version == 2
    finally:
        stop.set()
        router.shutdown()
        for _rid, handle in replicas:
            handle.shutdown()
