"""Request-scoped observability (obs.reqtrace / obs.slo / obs.profiler):
id sanitization, tail-sampling policy, phase stamping through the
batcher, SLO burn math, and the profiler's single-flight guard.

HTTP-level coverage (request-id echo over real sockets, /debug
endpoints, trace-merge containment, the loadgen/report join) lives in
tests/test_serve.py next to the serving fixtures.
"""

import threading
import time

import numpy as np
import pytest

from machine_learning_replications_tpu.obs import profiler, reqtrace, slo
from machine_learning_replications_tpu.obs.registry import REGISTRY
from machine_learning_replications_tpu.serve import MicroBatcher


# ---------------------------------------------------------------------------
# request ids
# ---------------------------------------------------------------------------


def test_request_id_sanitization():
    assert reqtrace.sanitize_request_id("abc-DEF_1.2") == "abc-DEF_1.2"
    assert reqtrace.sanitize_request_id("  padded-id ") == "padded-id"
    # hostile or degenerate inbound ids are REPLACED, never passed through
    for bad in (None, "", "   ", "evil\nheader", 'quo"te', "x" * 500,
                "space inside", "läßt"):
        rid = reqtrace.sanitize_request_id(bad)
        assert rid != bad and len(rid) == 16
        assert set(rid) <= set("0123456789abcdef")
    # two generated ids never collide
    assert reqtrace.new_request_id() != reqtrace.new_request_id()


# ---------------------------------------------------------------------------
# flight recorder: tail-based sampling
# ---------------------------------------------------------------------------


def _finished_trace(total_s: float, status: str = "ok") -> reqtrace.RequestTrace:
    tr = reqtrace.RequestTrace()
    tr.t_start = time.perf_counter() - total_s
    tr.finish(status)
    return tr


def test_recorder_keeps_failures_and_tail_drops_fast_majority():
    rec = reqtrace.FlightRecorder(
        capacity=64, tail_quantile=0.9, min_window=10
    )
    # warmup: bootstrap keeps everything until the window can rank
    for _ in range(10):
        assert rec.record(_finished_trace(0.010))
    # steady state: fast ok requests are dropped ...
    kept_fast = sum(rec.record(_finished_trace(0.001)) for _ in range(50))
    assert kept_fast <= 5  # ~p90 policy; a few stragglers at the boundary
    # ... the slow tail is kept ...
    assert rec.record(_finished_trace(0.500))
    # ... and every failure is kept regardless of latency
    for status in ("error", "timeout", "shed", "bad_request"):
        assert rec.record(_finished_trace(0.0001, status=status))
    by_status = [t["status"] for t in rec.snapshot()]
    assert {"error", "timeout", "shed", "bad_request"} <= set(by_status)
    stats = rec.stats()
    assert stats["dropped_total"] >= 45
    assert stats["tail_threshold_seconds"] is not None


def test_recorder_ring_is_bounded_and_newest_first():
    rec = reqtrace.FlightRecorder(capacity=8, min_window=10_000)  # all kept
    for i in range(30):
        tr = reqtrace.RequestTrace()
        tr.t_start = time.perf_counter() - 0.001
        tr.note(seq=i)  # before finish: a finished trace is immutable
        rec.record(tr.finish("ok"))
    snap = rec.snapshot()
    assert len(snap) == 8
    assert [t["seq"] for t in snap] == list(range(29, 21, -1))
    assert rec.snapshot(3) == snap[:3]
    assert rec.stats()["stored"] == 8 and rec.stats()["kept_total"] == 30


def test_recorder_rejects_bad_config():
    with pytest.raises(ValueError):
        reqtrace.FlightRecorder(tail_quantile=1.5)
    # capacity/window 0 must fail at construction, not as a
    # ZeroDivisionError on the first kept trace (--trace-capacity 0)
    with pytest.raises(ValueError):
        reqtrace.FlightRecorder(capacity=0)
    with pytest.raises(ValueError):
        reqtrace.FlightRecorder(window=0)


# ---------------------------------------------------------------------------
# phase stamping through the batcher
# ---------------------------------------------------------------------------


class _StubEngine:
    n_features = 17

    def predict(self, X):
        time.sleep(0.002)
        return X.mean(axis=1)

    def bucket_for(self, n):
        return 8


def test_batcher_stamps_trace_phases_partition():
    """The flush thread stamps queue_wait / batch_assembly /
    device_compute; with the caller's parse and respond phases they
    partition the request — durations sum to ≤ the end-to-end total."""
    b = MicroBatcher(_StubEngine(), max_batch_size=4, max_wait_ms=5.0)
    try:
        tr = reqtrace.RequestTrace("tr-1")
        tr.add_phase("parse", tr.t_start, time.perf_counter())
        fut = b.submit(np.full(17, 1.0), trace=tr)
        assert fut.result(timeout=5.0) == 1.0
        t0 = time.perf_counter()
        tr.add_phase("respond", tr.phase_end("device_compute", t0),
                     time.perf_counter())
        tr.finish("ok")
    finally:
        b.close()
    ph = tr.phase_seconds()
    # A device-path request records every phase except the host path's
    # host_compute (dual-path scoring stamps one compute phase or the
    # other, never both).
    assert set(ph) == set(reqtrace.PHASES) - {"host_compute"}
    assert ph["device_compute"] >= 0.002  # the stub's sleep is in there
    total = tr.total_s
    assert sum(ph.values()) <= total + 1e-6
    # complete attribution: the five phases cover ≥95% of the request
    assert sum(ph.values()) >= 0.95 * total
    assert tr.meta["batch_rows"] == 1 and tr.meta["bucket"] == 8
    assert tr.meta["flush_index"] == 0 and tr.meta["cold_compile"] is False
    assert tr.meta["flush_seq"] >= 1


def test_batcher_stamps_phases_on_engine_error():
    class Boom:
        n_features = 17

        def predict(self, X):
            raise RuntimeError("boom")

    b = MicroBatcher(Boom(), max_batch_size=2, max_wait_ms=1.0)
    try:
        tr = reqtrace.RequestTrace()
        fut = b.submit(np.full(17, 1.0), trace=tr)
        with pytest.raises(RuntimeError, match="boom"):
            fut.result(timeout=5.0)
    finally:
        b.close()
    # a failed flush still attributed the time it spent
    assert "queue_wait" in tr.phases and "batch_assembly" in tr.phases


def test_trace_immutable_after_finish():
    """Once finished, a trace rejects further stamps: on the 504 path the
    flush thread can win the cancel race and try to write compute phases
    after the handler already closed the trace — accepting them would
    push phase intervals past t_end and break the partition invariant."""
    tr = reqtrace.RequestTrace()
    tr.add_phase("parse", tr.t_start, time.perf_counter())
    tr.finish("timeout")
    end = tr.t_end
    tr.add_phase("device_compute", time.perf_counter(),
                 time.perf_counter() + 5.0)
    tr.note(cold_compile=True)
    tr.finish("ok")  # second finish ignored too
    assert tr.status == "timeout" and tr.t_end == end
    assert "device_compute" not in tr.phases and not tr.meta
    assert sum(tr.phase_seconds().values()) <= tr.total_s + 1e-6


# ---------------------------------------------------------------------------
# SLOs
# ---------------------------------------------------------------------------


def test_slo_declarations_validate():
    with pytest.raises(ValueError):
        slo.SLO("x", target=1.5)
    with pytest.raises(ValueError):
        slo.SLO("x", target=0.99, kind="latency")  # no threshold
    with pytest.raises(ValueError):
        slo.SLO("x", target=0.99, kind="nope")
    with pytest.raises(ValueError):
        slo.SLOTracker([slo.SLO("dup", 0.9, "availability"),
                        slo.SLO("dup", 0.9, "availability")])


def test_slo_burn_math():
    """10% bad traffic against a 1% budget burns at 10×, and the
    lifetime budget-remaining gauge integrates the damage."""
    tracker = slo.SLOTracker(
        [slo.SLO("lat", 0.99, "latency", threshold_s=0.1)], window=100,
    )
    for _ in range(90):
        tracker.observe(0.01, ok=True)     # good
    for _ in range(10):
        tracker.observe(0.5, ok=True)      # too slow -> bad
    snap = tracker.snapshot()[0]
    assert snap["requests_total"] == 100 and snap["bad_total"] == 10
    assert snap["window_good_ratio"] == pytest.approx(0.9)
    assert snap["burn_rate"] == pytest.approx(10.0)
    # budget 0.01, spent 0.10 of traffic -> 1 - 0.1/0.01 = -9 (blown)
    assert snap["error_budget_remaining_ratio"] == pytest.approx(-9.0)


def test_slo_availability_and_registry_exposition():
    import os
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
    try:
        import validate_metrics
    finally:
        sys.path.pop(0)

    tracker = slo.SLOTracker(slo.default_slos(), window=10)
    tracker.observe(0.01, ok=True)
    tracker.observe(0.01, ok=False)  # shed/timeout/error
    avail = next(
        s for s in tracker.snapshot() if s["name"] == "availability"
    )
    assert avail["bad_total"] == 1
    page = REGISTRY.render_prometheus()
    assert 'slo_burn_rate{slo="availability"}' in page
    assert 'slo_target_ratio{slo="availability"} 0.999' in page
    assert validate_metrics.validate(page) == [], \
        validate_metrics.validate(page)


# ---------------------------------------------------------------------------
# profiler: single flight, non-empty artifact
# ---------------------------------------------------------------------------


def test_profiler_rejects_bad_seconds(tmp_path):
    with pytest.raises(ValueError):
        profiler.capture(0.0, str(tmp_path))
    with pytest.raises(ValueError):
        profiler.capture(profiler.MAX_SECONDS + 1, str(tmp_path))


def test_profiler_capture_single_flight(tmp_path):
    """Concurrent captures: exactly one wins and returns a non-empty
    artifact; the rest fail fast with ProfilerBusy (never queue)."""
    import jax.numpy as jnp

    results, errors = [], []

    def churn():  # device work for the profiler to see
        x = jnp.ones((32, 32))
        for _ in range(5):
            x = (x @ x) / 32.0
        x.block_until_ready()

    def one():
        try:
            churn()
            results.append(profiler.capture(0.3, str(tmp_path)))
        except profiler.ProfilerBusy as exc:
            errors.append(exc)

    threads = [threading.Thread(target=one) for _ in range(3)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(results) == 1 and len(errors) == 2
    art = results[0]
    assert art["total_bytes"] > 0 and art["files"]
    assert all(f["bytes"] >= 0 for f in art["files"])
    assert not profiler.is_busy()
    # a second capture afterwards succeeds (the slot was released)
    art2 = profiler.capture(0.1, str(tmp_path))
    assert art2["profile_dir"] != art["profile_dir"]
