"""Orbax persistence: whole-model round-trip + boosting checkpoint/resume.

The reference's only persistence is one pickle written once and loaded by
``predict_hf.py:33-34``; it has no mid-training recovery (SURVEY.md §5).
These tests pin the framework's replacement: Orbax pytree checkpoints that
round-trip exactly, and a resumable boosting loop whose post-preemption
result is bit-identical to an unbroken fit.
"""

import jax
import numpy as np
import pytest

from machine_learning_replications_tpu.config import GBDTConfig
from machine_learning_replications_tpu.data.schema import selected_indices
from machine_learning_replications_tpu.models import gbdt, stacking, tree
from machine_learning_replications_tpu.persist import (
    REFERENCE_PKL_PATH,
    abstract_like,
    decode_pickle,
    import_stacking,
    orbax_io,
    restore_params,
    save_params,
)


@pytest.fixture(scope="module")
def fitted_forest(cohort_full):
    X, y, _ = cohort_full
    Xs = np.asarray(X[:, selected_indices()])
    cfg = GBDTConfig(n_estimators=20)
    params, aux = gbdt.fit(Xs, y, cfg)
    return Xs, y, cfg, params, aux


def test_forest_roundtrip(tmp_path, fitted_forest):
    Xs, _, _, params, _ = fitted_forest
    path = tmp_path / "forest"
    save_params(path, params)
    restored = restore_params(path, abstract_like(params))
    assert restored.max_depth == params.max_depth  # static field via template
    np.testing.assert_array_equal(
        np.asarray(restored.feature), np.asarray(params.feature)
    )
    np.testing.assert_array_equal(
        np.asarray(restored.value), np.asarray(params.value)
    )
    np.testing.assert_allclose(
        np.asarray(tree.predict_proba1(restored, Xs)),
        np.asarray(tree.predict_proba1(params, Xs)),
    )


def test_stacking_roundtrip_from_reference_pkl(tmp_path):
    params = import_stacking(decode_pickle(REFERENCE_PKL_PATH))
    path = tmp_path / "stacked"
    save_params(path, params)
    restored = restore_params(path, abstract_like(params))
    X = np.random.default_rng(7).normal(size=(32, 17))
    np.testing.assert_array_equal(
        np.asarray(stacking.predict_proba(restored, X)),
        np.asarray(stacking.predict_proba(params, X)),
    )


def test_save_model_sidecar_is_json_not_pickle(tmp_path):
    """``predict --model <dir>`` must never execute code from the model dir:
    the self-describing sidecar is JSON resolved against a fixed class
    registry (ADVICE.md round 1: the pickle sidecar was an arbitrary-code-
    execution vector on untrusted checkpoint directories)."""
    import json
    import os

    params = import_stacking(decode_pickle(REFERENCE_PKL_PATH))
    path = tmp_path / "model"
    orbax_io.save_model(path, params)
    files = os.listdir(path)
    assert not any(f.endswith(".pkl") for f in files), files
    with open(path / "pytree_template.json") as f:
        sidecar = json.load(f)  # must parse as plain JSON
    assert sidecar["root"]["cls"] == "StackingParams"

    restored = orbax_io.load_model(path)
    assert type(restored).__name__ == "StackingParams"
    X = np.random.default_rng(7).normal(size=(16, 17))
    np.testing.assert_array_equal(
        np.asarray(stacking.predict_proba(restored, X)),
        np.asarray(stacking.predict_proba(params, X)),
    )


def test_save_model_roundtrip_forest_statics(tmp_path, fitted_forest):
    """The sidecar carries non-array statics (max_depth) through JSON."""
    Xs, _, _, params, _ = fitted_forest
    path = tmp_path / "forest_model"
    orbax_io.save_model(path, params)
    restored = orbax_io.load_model(path)
    assert restored.max_depth == params.max_depth
    np.testing.assert_allclose(
        np.asarray(tree.predict_proba1(restored, Xs)),
        np.asarray(tree.predict_proba1(params, Xs)),
    )


def test_resumable_equals_unbroken(tmp_path, fitted_forest):
    Xs, y, cfg, params, aux = fitted_forest
    ckdir = tmp_path / "ck"
    with pytest.raises(orbax_io.SimulatedInterrupt):
        gbdt.fit_resumable(
            Xs, y, cfg,
            checkpoint_dir=str(ckdir), checkpoint_every=6,
            _interrupt_after_chunks=2,
        )
    # "New process": resume from the surviving checkpoints.
    resumed, aux2 = gbdt.fit_resumable(
        Xs, y, cfg, checkpoint_dir=str(ckdir), checkpoint_every=6
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.feature), np.asarray(params.feature)
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.threshold), np.asarray(params.threshold)
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.value), np.asarray(params.value)
    )
    np.testing.assert_array_equal(aux2["train_deviance"], aux["train_deviance"])


def test_pipeline_stage_resume_equals_unbroken(tmp_path, cohort):
    """Pipeline-level preemption-resume (VERDICT r2 missing #2): a fit
    interrupted after the GBDT member stage, re-entered with the same
    checkpoint dir, must restore the finished stages (impute → select →
    svc → gbdt) instead of recomputing, and the final params must equal an
    unbroken fit's bit for bit (stage outputs are deterministic)."""
    from machine_learning_replications_tpu.config import (
        ExperimentConfig, GBDTConfig, LassoSelectConfig, SVCConfig,
    )
    from machine_learning_replications_tpu.models import pipeline

    X, y, _ = cohort
    X = np.asarray(X[:220])
    y = np.asarray(y[:220])
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=8),
        svc=SVCConfig(platt_cv=2, max_iter=300),
        select=LassoSelectConfig(cv_folds=3, n_alphas=20),
    )
    unbroken, _ = pipeline.fit_pipeline(X, y, cfg)

    ckdir = str(tmp_path / "stages")
    with pytest.raises(orbax_io.SimulatedInterrupt):
        pipeline.fit_pipeline(
            X, y, cfg, checkpoint_dir=ckdir, _interrupt_after="member_gbdt"
        )
    ck = orbax_io.StageCheckpointer(ckdir)
    assert ck.completed("impute") and ck.completed("member_gbdt")
    assert not ck.completed("meta")

    # "New process": finished stages restore, the rest compute.
    resumed, _ = pipeline.fit_pipeline(X, y, cfg, checkpoint_dir=ckdir)
    assert ck.completed("meta")
    for got, want in zip(
        jax.tree.leaves(resumed), jax.tree.leaves(unbroken)
    ):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))

    # Re-entry after completion restores everything (no recompute, no drift).
    again, _ = pipeline.fit_pipeline(X, y, cfg, checkpoint_dir=ckdir)
    for got, want in zip(jax.tree.leaves(again), jax.tree.leaves(unbroken)):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_stage_checkpoint_dir_rejects_different_inputs(tmp_path, cohort):
    """Re-entering a stage-checkpoint dir with different (X, y, cfg) must
    fail loudly, not silently restore the other fit's stages."""
    from machine_learning_replications_tpu.config import (
        ExperimentConfig, GBDTConfig, LassoSelectConfig, SVCConfig,
    )
    from machine_learning_replications_tpu.models import pipeline

    X, y, _ = cohort
    X, y = np.asarray(X[:150]), np.asarray(y[:150])
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=4),
        svc=SVCConfig(platt_cv=2, max_iter=150),
        select=LassoSelectConfig(cv_folds=3, n_alphas=10),
    )
    ckdir = str(tmp_path / "fp")
    pipeline.fit_pipeline(X, y, cfg, checkpoint_dir=ckdir)
    with pytest.raises(RuntimeError, match="fingerprint"):
        pipeline.fit_pipeline(X[:120], y[:120], cfg, checkpoint_dir=ckdir)
    # same inputs still restore fine
    pipeline.fit_pipeline(X, y, cfg, checkpoint_dir=ckdir)


def test_stage_checkpointer_recovers_from_torn_sidecar(tmp_path):
    """A truncated sidecar (crash mid-write before the atomic-replace fix,
    or torn tensorstore files) must not wedge resume: the stage falls back
    to recompute (ADVICE r2 medium)."""
    import os

    calls = {"n": 0}

    def compute():
        calls["n"] += 1
        return (np.arange(4.0), np.ones(3))

    ck = orbax_io.StageCheckpointer(str(tmp_path / "s"))
    out1 = ck.run("stage_a", compute)
    assert calls["n"] == 1
    # Corrupt the sidecar in place — simulates a pre-fix torn write.
    sidecar = os.path.join(str(tmp_path / "s"), "stage_a", "pytree_template.json")
    with open(sidecar, "w") as f:
        f.write('{"format": 1, "root": {"seq": [')
    out2 = ck.run("stage_a", compute)
    assert calls["n"] == 2  # recomputed, not crashed
    np.testing.assert_array_equal(np.asarray(out2[0]), np.asarray(out1[0]))
    # ...and the re-written checkpoint is whole again.
    out3 = ck.run("stage_a", compute)
    assert calls["n"] == 2
    np.testing.assert_array_equal(np.asarray(out3[0]), np.asarray(out1[0]))


def test_resumable_deeper_path(tmp_path, cohort_full):
    X, y, _ = cohort_full
    Xs = np.asarray(X[:, selected_indices()])
    cfg = GBDTConfig(n_estimators=8, max_depth=2)
    direct, _ = gbdt.fit(Xs, y, cfg)
    with pytest.raises(orbax_io.SimulatedInterrupt):
        gbdt.fit_resumable(
            Xs, y, cfg,
            checkpoint_dir=str(tmp_path / "ck2"), checkpoint_every=3,
            _interrupt_after_chunks=1,
        )
    resumed, _ = gbdt.fit_resumable(
        Xs, y, cfg, checkpoint_dir=str(tmp_path / "ck2"), checkpoint_every=3
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.feature), np.asarray(direct.feature)
    )
    np.testing.assert_array_equal(
        np.asarray(resumed.value), np.asarray(direct.value)
    )


def test_cv_substage_resume_equals_unbroken(tmp_path, cohort):
    """The CV meta pass is the longest stage at scale and is now split
    into per-member OOF sub-stages (meta_svc_oof / meta_gbdt_oof /
    meta_lg_oof): a preemption right after the GBDT OOF column must
    restore the SVC and GBDT columns on re-entry — only the LG column and
    the meta-LR recompute — and still equal an unbroken fit bit for bit."""
    from machine_learning_replications_tpu.config import (
        ExperimentConfig, GBDTConfig, LassoSelectConfig, SVCConfig,
    )
    from machine_learning_replications_tpu.models import pipeline

    X, y, _ = cohort
    X = np.asarray(X[:220])
    y = np.asarray(y[:220])
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=8),
        svc=SVCConfig(platt_cv=2, max_iter=300),
        select=LassoSelectConfig(cv_folds=3, n_alphas=20),
    )
    unbroken, _ = pipeline.fit_pipeline(X, y, cfg)

    ckdir = str(tmp_path / "cv_stages")
    with pytest.raises(orbax_io.SimulatedInterrupt):
        pipeline.fit_pipeline(
            X, y, cfg, checkpoint_dir=ckdir, _interrupt_after="meta_gbdt_oof"
        )
    ck = orbax_io.StageCheckpointer(ckdir)
    assert ck.completed("meta_svc_oof") and ck.completed("meta_gbdt_oof")
    assert not ck.completed("meta_lg_oof") and not ck.completed("meta")

    resumed, _ = pipeline.fit_pipeline(X, y, cfg, checkpoint_dir=ckdir)
    assert ck.completed("meta")
    for got, want in zip(
        jax.tree.leaves(resumed), jax.tree.leaves(unbroken)
    ):
        np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


# ---------------------------------------------------------------------------
# quality reference profile: carried by the checkpoint, absent in old dirs
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def fitted_small_pipeline(cohort):
    """One small-but-real fit_pipeline shared by the profile tests."""
    from machine_learning_replications_tpu.config import (
        ExperimentConfig, GBDTConfig, LassoSelectConfig, SVCConfig,
    )
    from machine_learning_replications_tpu.models import pipeline

    X, y, _ = cohort
    X, y = np.asarray(X[:150]), np.asarray(y[:150])
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=4),
        svc=SVCConfig(platt_cv=2, max_iter=150),
        select=LassoSelectConfig(cv_folds=3, n_alphas=10),
    )
    params, _ = pipeline.fit_pipeline(X, y, cfg)
    return params


def test_fit_pipeline_builds_quality_profile_and_roundtrips(
    tmp_path, fitted_small_pipeline
):
    """Tentpole contract: fit_pipeline records the model's own drift
    baseline — per-feature histograms over the post-impute post-select
    X[n, 17] plus the training score distribution — and the checkpoint
    carries it bit-for-bit through save_model/load_model (the sidecar's
    plain mapping node, no new registry class)."""
    from machine_learning_replications_tpu.obs import quality

    params = fitted_small_pipeline
    prof = {k: np.asarray(v) for k, v in params.quality.items()}
    F = int(np.asarray(params.support_mask).sum())
    B = quality.DEFAULT_FEATURE_BINS
    assert prof["bin_edges"].shape == (F, B + 1)
    assert prof["bin_counts"].shape == (F, B)
    assert int(prof["n_rows"]) == 150
    assert prof["score_counts"].sum() == 150
    assert np.isfinite(prof["calib_pos_rate"]).any()  # labels were present
    path = str(tmp_path / "with_profile")
    orbax_io.save_model(path, params)
    restored = orbax_io.load_model(path)
    for k, v in prof.items():
        np.testing.assert_array_equal(np.asarray(restored.quality[k]), v)
    # and a monitor constructs straight from the restored profile (the
    # serve-time key/shape contract)
    from machine_learning_replications_tpu.obs.registry import (
        MetricsRegistry,
    )

    quality.QualityMonitor(restored.quality, registry=MetricsRegistry())


def test_profile_less_checkpoint_loads_with_single_journaled_warning(
    tmp_path, fitted_small_pipeline
):
    """Backward compat: a checkpoint dir written BEFORE reference profiles
    existed (its sidecar's PipelineParams node has no 'quality' field at
    all) must restore cleanly — quality None, monitoring simply disabled —
    with exactly one journaled warning naming the gap."""
    import json as _json

    from machine_learning_replications_tpu.obs import journal

    params = fitted_small_pipeline
    path = str(tmp_path / "old_format")
    # Saving with quality=None writes the same Orbax array tree an old
    # build wrote (None leaves are absent from the pytree); stripping the
    # sidecar field reproduces the old sidecar byte-structure exactly.
    orbax_io.save_model(path, params.replace(quality=None))
    sc_path = tmp_path / "old_format" / "pytree_template.json"
    sidecar = _json.loads(sc_path.read_text())
    assert sidecar["root"]["fields"]["quality"] == {"static": None}
    del sidecar["root"]["fields"]["quality"]
    sc_path.write_text(_json.dumps(sidecar))
    # An old build wrote no integrity manifest either — and the current
    # one covers the sidecar, so the edit above would (correctly) read as
    # corruption. Delete it to reproduce the legacy layout exactly;
    # manifest-less checkpoints restore unverified by design.
    (tmp_path / "old_format" / "integrity.json").unlink()

    jrn = journal.RunJournal(tmp_path / "restore.jsonl", command="predict")
    journal.set_journal(jrn)
    try:
        restored = orbax_io.load_model(path)
    finally:
        journal.set_journal(None)
        jrn.close()
    assert restored.quality is None
    assert np.asarray(restored.ensemble.meta.coef).shape == np.asarray(
        params.ensemble.meta.coef
    ).shape
    events = [
        _json.loads(line) for line in open(tmp_path / "restore.jsonl")
    ]
    warnings_ = [
        e for e in events if e.get("kind") == "quality_profile_missing"
    ]
    assert len(warnings_) == 1
    assert warnings_[0]["path"] == orbax_io.os.path.abspath(path)
    # a checkpoint WITH a profile journals nothing
    path2 = str(tmp_path / "new_format")
    orbax_io.save_model(path2, params)
    jrn2 = journal.RunJournal(tmp_path / "restore2.jsonl", command="predict")
    journal.set_journal(jrn2)
    try:
        orbax_io.load_model(path2)
    finally:
        journal.set_journal(None)
        jrn2.close()
    events2 = [
        _json.loads(line) for line in open(tmp_path / "restore2.jsonl")
    ]
    assert not [
        e for e in events2 if e.get("kind") == "quality_profile_missing"
    ]
