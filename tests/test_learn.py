"""Continual learning (learn/): shadow comparator golden values, verdict
thresholds both sides, capture-buffer rotation/bounds, trigger
debounce/cooldown/schedule, quality transition ring + rebase, promotion
park/refuse, and the warm-refit → shadow → gate arc on a real (small)
ensemble.

The comparator math tests pin ``score_divergence``/``cohort_quality``/
``mean_disagreement`` to values computable by hand — everything
downstream of them (gauges, verdict, journal) is formatting, so these
goldens are the shadow contract's spec (docs/CONTINUAL.md)."""

import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from machine_learning_replications_tpu.learn import capture as capturemod
from machine_learning_replications_tpu.learn import retrain as retrainmod  # noqa: F401 — registers learn_retrain_* families
from machine_learning_replications_tpu.learn import promote as promotemod
from machine_learning_replications_tpu.learn import shadow as shadowmod
from machine_learning_replications_tpu.learn import trigger as triggermod
from machine_learning_replications_tpu.obs import journal, quality
from machine_learning_replications_tpu.obs.registry import (
    REGISTRY,
    MetricsRegistry,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
try:
    import validate_metrics
finally:
    sys.path.pop(0)


def _journaled(tmp_path, fn):
    """Run ``fn`` under a fresh journal; return its parsed events."""
    path = tmp_path / "journal.jsonl"
    jrn = journal.RunJournal(path, command="test")
    journal.set_journal(jrn)
    try:
        fn()
    finally:
        journal.set_journal(None)
        jrn.close()
    return [json.loads(line) for line in open(path)]


# ---------------------------------------------------------------------------
# comparator math: golden values
# ---------------------------------------------------------------------------


def test_score_divergence_identical_streams_is_zero():
    p = np.linspace(0.05, 0.95, 200)
    d = shadowmod.score_divergence(p, p.copy())
    assert d["rows"] == 200
    assert d["divergence_mean"] == 0.0
    assert d["divergence_p95"] == 0.0
    assert d["divergence_max"] == 0.0
    assert d["flip_rate"] == 0.0
    assert d["score_psi"] == 0.0


def test_score_divergence_known_shift_golden():
    """A constant +0.1 shift: mean/p95/max all exactly 0.1, the flip rate
    counts exactly the rows the shift carries across 0.5, and the score
    PSI equals the standalone ``quality.psi`` oracle on the same bins."""
    p_live = np.array([0.10, 0.30, 0.45, 0.48, 0.60, 0.80])
    p_cand = p_live + 0.1
    d = shadowmod.score_divergence(p_live, p_cand)
    assert d["divergence_mean"] == pytest.approx(0.1)
    assert d["divergence_p95"] == pytest.approx(0.1)
    assert d["divergence_max"] == pytest.approx(0.1)
    # rows at 0.45 and 0.48 cross the 0.5 operating point: 2 of 6
    assert d["flip_rate"] == pytest.approx(2 / 6)
    bins = quality.DEFAULT_SCORE_BINS
    live_c = np.bincount(
        quality._score_bin_indices(p_live, bins), minlength=bins
    )
    cand_c = np.bincount(
        quality._score_bin_indices(p_cand, bins), minlength=bins
    )
    assert d["score_psi"] == pytest.approx(quality.psi(live_c, cand_c))


def test_score_divergence_edge_cases():
    empty = shadowmod.score_divergence(np.zeros(0), np.zeros(0))
    assert empty["rows"] == 0
    # strict JSON: not-computable is None, never NaN
    assert all(
        empty[k] is None for k in (
            "divergence_mean", "divergence_p95", "divergence_max",
            "flip_rate", "score_psi",
        )
    )
    json.dumps(empty, allow_nan=False)
    with pytest.raises(ValueError, match="differ in length"):
        shadowmod.score_divergence(np.zeros(3), np.zeros(4))
    with pytest.raises(ValueError, match="finite"):
        shadowmod.score_divergence(
            np.array([0.1, np.nan]), np.array([0.1, 0.2])
        )


def test_mean_disagreement_golden():
    # two members, constant gap 0.2 → mean pairwise disagreement 0.2
    m = np.column_stack([np.full(10, 0.4), np.full(10, 0.6)])
    assert shadowmod.mean_disagreement(m) == pytest.approx(0.2)
    # three members at 0.2/0.4/0.8: pairs |.2|,|.6|,|.4| → mean 0.4
    m3 = np.tile(np.array([0.2, 0.4, 0.8]), (5, 1))
    assert shadowmod.mean_disagreement(m3) == pytest.approx(0.4)
    assert shadowmod.mean_disagreement(None) is None
    assert shadowmod.mean_disagreement(np.zeros((5, 1))) is None
    assert shadowmod.mean_disagreement(np.zeros((0, 3))) is None


def test_cohort_quality_judges_against_the_given_profile():
    rng = np.random.default_rng(11)
    ref = rng.normal(size=(4000, 3))
    prof = quality.build_reference_profile(
        ref, np.full(4000, 0.5)
    )
    same = shadowmod.cohort_quality(prof, rng.normal(size=(2000, 3)))
    assert same["status"] == "ok"
    assert same["worst_psi"] < quality.DEFAULT_WARN_PSI
    shifted = rng.normal(size=(2000, 3))
    shifted[:, 1] += 3.0
    drifted = shadowmod.cohort_quality(prof, shifted)
    assert drifted["status"] == "alert"
    assert drifted["worst_feature_index"] == 1
    assert drifted["worst_psi"] > quality.DEFAULT_ALERT_PSI
    with pytest.raises(ValueError, match="describes 3 features"):
        shadowmod.cohort_quality(prof, np.zeros((10, 4)))
    with pytest.raises(ValueError, match="finite"):
        shadowmod.cohort_quality(prof, np.full((10, 3), np.nan))


# ---------------------------------------------------------------------------
# verdict thresholds, both sides
# ---------------------------------------------------------------------------


def _stats(**overrides):
    base = {
        "rows": 500,
        "divergence_mean": 0.05,
        "divergence_p95": 0.10,
        "divergence_max": 0.20,
        "flip_rate": 0.02,
        "score_psi": 0.5,
        "disagreement_delta": 0.01,
        "candidate_quality": {"status": "ok", "worst_psi": 0.05,
                              "rows": 500},
    }
    base.update(overrides)
    return base


def test_judge_passes_below_every_threshold():
    v = shadowmod.judge(_stats(), shadowmod.ShadowThresholds())
    assert v["pass"] and v["reasons"] == []


@pytest.mark.parametrize("key,bound_attr", [
    ("divergence_mean", "max_divergence_mean"),
    ("divergence_p95", "max_divergence_p95"),
    ("flip_rate", "max_flip_rate"),
    ("score_psi", "max_score_psi"),
    ("disagreement_delta", "max_disagreement_delta"),
])
def test_judge_each_threshold_fails_just_above_passes_at(key, bound_attr):
    th = shadowmod.ShadowThresholds()
    bound = getattr(th, bound_attr)
    at = shadowmod.judge(_stats(**{key: bound}), th)
    assert at["pass"], f"{key} == bound must pass: {at['reasons']}"
    over = shadowmod.judge(_stats(**{key: bound + 1e-6}), th)
    assert not over["pass"]
    assert any(key in r for r in over["reasons"])


def test_judge_fails_closed_on_missing_evidence():
    th = shadowmod.ShadowThresholds()
    few = shadowmod.judge(_stats(rows=th.min_rows - 1), th)
    assert not few["pass"] and "min_rows" in few["reasons"][0]
    noprof = shadowmod.judge(_stats(candidate_quality=None), th)
    assert not noprof["pass"]
    assert "no quality reference profile" in noprof["reasons"][0]
    permissive = shadowmod.ShadowThresholds(require_candidate_profile=False)
    assert shadowmod.judge(_stats(candidate_quality=None), permissive)["pass"]
    bad_self = shadowmod.judge(
        _stats(candidate_quality={"status": "alert", "worst_psi": 0.9,
                                  "rows": 500}),
        th,
    )
    assert not bad_self["pass"]
    assert "candidate self-quality" in bad_self["reasons"][0]


def test_judge_verdict_is_strict_json():
    stats = _stats(divergence_mean=float("nan"))
    # NaN sneaking into a stats block must land as null in the verdict
    v = shadowmod.judge(stats, shadowmod.ShadowThresholds())
    json.dumps(v, allow_nan=False)
    assert v["stats"]["divergence_mean"] is None


def test_shadow_gauges_validator_clean_in_all_states():
    """The learn_shadow_* families must render a strict-validator-clean
    page both while holding the NaN "no data" value and after an export;
    the JSON snapshot renders those NaNs as null."""
    page = REGISTRY.render_prometheus()
    assert validate_metrics.validate(page) == []
    for name in (
        "learn_shadow_divergence_mean", "learn_shadow_flip_rate",
        "learn_shadow_score_psi", "learn_shadow_candidate_worst_psi",
        "learn_shadow_rows", "learn_shadow_evaluations_total",
        "learn_trigger_total", "learn_capture_rows_total",
        "learn_promotions_total", "learn_retrain_total",
    ):
        assert name in page, f"{name} missing from scrape"
    json.dumps(REGISTRY.snapshot(), allow_nan=False)
    # export a no-data stats block (all None → NaN gauges), then a real one
    shadowmod._export({"rows": 0})
    assert validate_metrics.validate(REGISTRY.render_prometheus()) == []
    assert REGISTRY.snapshot()["learn_shadow_divergence_mean"] is None
    shadowmod._export(_stats())
    page = REGISTRY.render_prometheus()
    assert validate_metrics.validate(page) == []
    snap = REGISTRY.snapshot()
    assert snap["learn_shadow_divergence_mean"] == pytest.approx(0.05)
    assert snap["learn_shadow_candidate_status"] == 0.0


# ---------------------------------------------------------------------------
# capture buffer
# ---------------------------------------------------------------------------


def _patient_line(**overrides) -> bytes:
    from machine_learning_replications_tpu.data.examples import (
        EXAMPLE_PATIENT,
    )

    p = dict(EXAMPLE_PATIENT)
    p.update(overrides)
    return json.dumps(p).encode()


def test_capture_rotates_and_bounds_the_window(tmp_path):
    cap = capturemod.CohortCapture(
        tmp_path, rows_per_shard=4, max_shards=2
    )
    for i in range(20):
        cap.append_line(_patient_line(Max_Wall_Thick=40 + i))
    stats = cap.stats()
    # 20 rows over 4-row shards = 5 shards; only the newest 2 retained
    assert stats["shards"] == 2
    assert stats["rows_appended"] == 20
    assert stats["rows_retained"] == 8
    on_disk = sorted(os.listdir(tmp_path))
    assert on_disk == ["cohort-00003.jsonl", "cohort-00004.jsonl"]
    cap.close()
    # a restarted capture resumes the sequence instead of overwriting
    cap2 = capturemod.CohortCapture(
        tmp_path, rows_per_shard=4, max_shards=2
    )
    cap2.append_line(_patient_line(Max_Wall_Thick=99))
    assert "cohort-00005.jsonl" in os.listdir(tmp_path)
    cap2.close()


def test_capture_normalizes_and_skips_empty_bodies(tmp_path):
    cap = capturemod.CohortCapture(tmp_path, rows_per_shard=10)
    cap.append_line(b'{"a": 1,\r\n "b": 2}')  # newline inside one body
    cap.append_line(b"")
    cap.append_line("   ")
    cap.append_line({"c": 3})
    cap.close()
    lines = open(tmp_path / "cohort-00000.jsonl", "rb").read().splitlines()
    assert len(lines) == 2
    assert json.loads(lines[0]) == {"a": 1, "b": 2}
    assert json.loads(lines[1]) == {"c": 3}


def test_load_recent_newest_rows_oldest_first_with_quarantine(tmp_path):
    cap = capturemod.CohortCapture(tmp_path, rows_per_shard=8)
    ages = list(range(30, 50))
    for age in ages:
        cap.append_line(_patient_line(Max_Wall_Thick=age))
    cap.append_line(b'{"not": "a patient"}')
    cap.append_line(b"garbage {{{")
    cap.close()
    X, n_bad = capturemod.load_recent(tmp_path, max_rows=10)
    assert n_bad == 2
    age_col = list(
        json.loads(_patient_line().decode()).keys()
    ).index("Max_Wall_Thick")
    # the row budget covers the newest 10 captured LINES (2 of which are
    # the malformed tail, dropped + counted), restored oldest-first
    assert list(X[:, age_col]) == [float(a) for a in ages[-8:]]
    with pytest.raises(ValueError, match="max_rows"):
        capturemod.load_recent(tmp_path, max_rows=0)


def test_capture_validates_construction(tmp_path):
    with pytest.raises(ValueError):
        capturemod.CohortCapture(tmp_path, rows_per_shard=0)
    with pytest.raises(ValueError):
        capturemod.CohortCapture(tmp_path, max_shards=0)


# ---------------------------------------------------------------------------
# trigger policy
# ---------------------------------------------------------------------------


class _Clock:
    def __init__(self):
        self.t = 100.0

    def __call__(self):
        return self.t


def _poll(status, url="http://r1", psi=0.5, feature="Syncope"):
    return {
        "url": url, "ok": status is not None, "status": status,
        "worst_feature": feature, "worst_psi": psi,
        "transitions": [],
    }


def test_trigger_debounce_then_fire_then_cooldown(tmp_path):
    clk = _Clock()
    policy = triggermod.TriggerPolicy(
        alert_streak=3, cooldown_s=60.0, clock=clk
    )
    decisions = []

    def drive():
        for _ in range(2):
            decisions.append(policy.observe([_poll("alert", psi=2.0)]))
            clk.t += 1
        decisions.append(policy.observe([_poll("alert", psi=2.5)]))
        clk.t += 1
        # immediately alert again: suppressed by cooldown even at streak
        for _ in range(3):
            decisions.append(policy.observe([_poll("alert")]))
            clk.t += 1
        # past the cooldown the streak has rebuilt → fires again
        clk.t += 60
        decisions.append(policy.observe([_poll("alert", psi=3.0)]))

    events = _journaled(tmp_path, drive)
    assert decisions[0] is None and decisions[1] is None
    assert decisions[2] is not None
    assert decisions[2]["reason"] == "alert"
    assert decisions[2]["worst_feature"] == "Syncope"
    assert decisions[2]["worst_psi"] == 2.5
    assert decisions[3] is None and decisions[4] is None
    # streak rebuilt to 3 inside the cooldown → suppressed_cooldown
    assert decisions[5] is None
    assert decisions[6] is not None and decisions[6]["worst_psi"] == 3.0
    kinds = [
        (e["fired"], e.get("suppressed_by"))
        for e in events if e["kind"] == "learn_trigger"
    ]
    # every decision journaled: 2 debounce, fire, 2 debounce, cooldown, fire
    assert kinds == [
        (False, "debounce"), (False, "debounce"), (True, None),
        (False, "debounce"), (False, "debounce"), (False, "cooldown"),
        (True, None),
    ]


def test_trigger_streak_resets_on_clean_poll():
    clk = _Clock()
    policy = triggermod.TriggerPolicy(alert_streak=2, cooldown_s=0,
                                      clock=clk)
    assert policy.observe([_poll("alert")]) is None
    assert policy.observe([_poll("ok")]) is None  # reset
    assert policy.observe([_poll("alert")]) is None  # streak back to 1
    assert policy.observe([_poll("alert")]) is not None
    # an unreachable fleet neither advances nor resets the streak
    policy2 = triggermod.TriggerPolicy(alert_streak=2, cooldown_s=0,
                                       clock=clk)
    assert policy2.observe([_poll("alert")]) is None
    assert policy2.observe([_poll(None)]) is None  # unreachable
    assert policy2.observe([_poll("alert")]) is not None


def test_trigger_schedule_fires_without_drift(tmp_path):
    clk = _Clock()
    policy = triggermod.TriggerPolicy(
        alert_streak=2, cooldown_s=30.0, schedule_s=100.0, clock=clk
    )

    fired = []

    def drive():
        fired.append(policy.observe([_poll("ok")]))
        clk.t += 99
        fired.append(policy.observe([_poll("ok")]))
        clk.t += 2
        fired.append(policy.observe([_poll("ok")]))
        # next schedule anchor is the last fire; cooldown also applies
        clk.t += 20
        fired.append(policy.observe([_poll("ok")]))
        clk.t += 81
        fired.append(policy.observe([_poll("ok")]))

    events = _journaled(tmp_path, drive)
    assert fired[0] is None and fired[1] is None
    assert fired[2] is not None and fired[2]["reason"] == "schedule"
    assert fired[3] is None
    assert fired[4] is not None and fired[4]["reason"] == "schedule"
    journaled = [e for e in events if e["kind"] == "learn_trigger"]
    assert [e["reason"] for e in journaled if e["fired"]] == [
        "schedule", "schedule",
    ]


def test_trigger_policy_validates_construction():
    with pytest.raises(ValueError):
        triggermod.TriggerPolicy(alert_streak=0)
    with pytest.raises(ValueError):
        triggermod.TriggerPolicy(cooldown_s=-1)
    with pytest.raises(ValueError):
        triggermod.TriggerPolicy(schedule_s=0)


# ---------------------------------------------------------------------------
# quality transition ring + rebase (the satellite + the promotion rebase)
# ---------------------------------------------------------------------------


def _stable_monitor(n_ref=4000, window=1024, **kw):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(n_ref, 17))
    scores = 1.0 / (1.0 + np.exp(-X @ rng.normal(size=17) / 4.0))
    prof = quality.build_reference_profile(
        X, scores, (scores > 0.5).astype(float)
    )
    kw.setdefault("refresh_interval_s", 0.0)
    mon = quality.QualityMonitor(
        prof, window=window, registry=MetricsRegistry(), **kw
    )
    return mon, X, scores, rng


def test_snapshot_transition_ring_records_the_arc(tmp_path):
    mon, X, scores, rng = _stable_monitor(window=512, min_rows=100)

    def drive():
        bad = rng.normal(size=(512, 17))
        bad[:, 0] += 5.0
        mon.observe_batch(bad, rng.choice(scores, size=512))
        assert mon.status == "alert"
        mon.observe_batch(
            rng.normal(size=(512, 17)), rng.choice(scores, size=512)
        )
        assert mon.status == "ok"

    _journaled(tmp_path, drive)
    snap = mon.snapshot()
    arcs = [(t["from_status"], t["to_status"]) for t in snap["transitions"]]
    assert arcs == [("ok", "alert"), ("alert", "ok")]
    first = snap["transitions"][0]
    assert first["worst_feature"] == "Obstructive HCM"
    assert first["worst_psi"] > quality.DEFAULT_ALERT_PSI
    assert first["window_rows"] == 512
    assert "ts" in first
    json.dumps(snap, allow_nan=False)


def test_transition_ring_is_bounded():
    mon, X, scores, rng = _stable_monitor(window=256, min_rows=50)
    clean = rng.normal(size=(256, 17))
    bad = clean.copy()
    bad[:, 3] += 5.0
    for _ in range(quality.TRANSITION_HISTORY):
        mon.observe_batch(bad, rng.choice(scores, size=256))
        mon.observe_batch(clean, rng.choice(scores, size=256))
    ring = mon.snapshot()["transitions"]
    assert len(ring) == quality.TRANSITION_HISTORY
    # newest-last: the final entry is the latest recovery
    assert ring[-1]["to_status"] == "ok"


def test_rebase_adopts_profile_and_recovery_is_earned(tmp_path):
    """The promotion path's monitor rebase: alert under shifted traffic,
    rebase onto a profile built FROM that shifted cohort, and the status
    returns to ok only after fresh post-rebase traffic — journaled as a
    real transition."""
    mon, X, scores, rng = _stable_monitor(window=512, min_rows=100)
    shifted = rng.normal(size=(2000, 17)) + 2.0

    def drive():
        mon.observe_batch(shifted[:512], rng.choice(scores, size=512))
        assert mon.status == "alert"
        new_prof = quality.build_reference_profile(
            shifted, np.clip(rng.choice(scores, size=2000), 0, 1)
        )
        mon.rebase(new_prof)
        # the rebase clears the window but does NOT declare recovery
        assert mon.status == "alert"
        snap = mon.snapshot()
        assert snap["window_rows"] == 0
        assert snap["score_psi"] is None
        # fresh traffic matching the NEW baseline earns the recovery
        mon.observe_batch(
            rng.normal(size=(512, 17)) + 2.0,
            rng.choice(scores, size=512),
        )
        assert mon.status == "ok"

    events = _journaled(tmp_path, drive)
    kinds = [e["kind"] for e in events]
    assert "quality_rebased" in kinds
    trans = [e for e in events if e["kind"] == "quality_status"]
    assert [(e["from_status"], e["to_status"]) for e in trans] == [
        ("ok", "alert"), ("alert", "ok"),
    ]
    # rebase happened between the two transitions
    assert kinds.index("quality_rebased") > kinds.index("quality_status")


def test_rebase_rejects_mismatched_width():
    mon, X, scores, rng = _stable_monitor()
    narrow = quality.build_reference_profile(
        rng.normal(size=(500, 5)), np.full(500, 0.5)
    )
    with pytest.raises(ValueError, match="5 features"):
        mon.rebase(narrow)
    # untouched: still judging against the original 17-wide profile
    mon.observe_batch(rng.normal(size=(512, 17)),
                      rng.choice(scores, size=512))
    assert mon.status == "ok"


# ---------------------------------------------------------------------------
# promotion gate mechanics (jax-free half)
# ---------------------------------------------------------------------------


def test_park_writes_refusal_and_blocks_publish(tmp_path):
    cand = tmp_path / "candidate"
    cand.mkdir()
    verdict = {"pass": False, "reasons": ["flip_rate 0.4 exceeds 0.1"]}

    def drive():
        path = promotemod.park(cand, verdict)
        assert os.path.basename(path) == promotemod.REFUSED_FILE
        refused = json.load(open(path))
        assert refused["kind"] == "learn_promotion_refused"
        assert refused["verdict"]["reasons"] == verdict["reasons"]

    events = _journaled(tmp_path, drive)
    assert promotemod.is_parked(cand)
    refusals = [
        e for e in events
        if e["kind"] == "learn_promotion" and e["result"] == "refused"
    ]
    assert len(refusals) == 1
    with pytest.raises(RuntimeError, match="refused"):
        promotemod.publish_candidate(cand, tmp_path / "live")


def test_promote_refuses_failing_verdict_without_touching_fleet(tmp_path):
    cand = tmp_path / "cand"
    cand.mkdir()
    out = promotemod.promote(
        cand, tmp_path / "live", "http://127.0.0.1:9",  # unroutable
        {"pass": False, "reasons": ["rows below min"]},
    )
    assert out["result"] == "refused"
    assert promotemod.is_parked(cand)
    # no deploy was attempted: the unroutable router URL never mattered


def test_promote_via_router_reads_deploy_report(tmp_path):
    from machine_learning_replications_tpu.serve.transport import (
        EventLoopHttpServer,
    )

    class _StubRouter:
        def __init__(self):
            self.bodies = []
            self.response = {"deploy": {"result": "ok", "replicas": []}}
            self.code = 200

        def handle_request(self, req, rsp):
            self.bodies.append(json.loads(req.body))
            rsp.send_json(self.code, self.response)

        def handle_protocol_error(self, exc, rsp):
            rsp.send_json(exc.code, {"error": exc.message}, close=True)

    stub = _StubRouter()
    httpd = EventLoopHttpServer(("127.0.0.1", 0), stub)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    try:
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        report = promotemod.promote_via_router(url, "/ck/model")
        assert report["result"] == "ok"
        assert stub.bodies == [{"model": "/ck/model"}]
        # an HTTP-error reply that still carries a deploy report (the
        # 409 already-in-progress shape) is returned, not raised
        stub.code = 409
        stub.response = {"deploy": {"result": "failed",
                                    "error": "in progress"}}
        report = promotemod.promote_via_router(url, "/ck/model")
        assert report["result"] == "failed"
        # an HTTP error without a report is a transport failure
        stub.code = 500
        stub.response = {"error": "boom"}
        with pytest.raises(RuntimeError, match="boom"):
            promotemod.promote_via_router(url, "/ck/model")
    finally:
        httpd.shutdown()
        httpd.server_close()
    with pytest.raises(RuntimeError, match="failed"):
        promotemod.promote_via_router("http://127.0.0.1:9", "/ck/model",
                                      timeout_s=0.5)


# ---------------------------------------------------------------------------
# loadgen perturb-until / revert-file (the client satellite)
# ---------------------------------------------------------------------------


def _loadgen():
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    try:
        import loadgen
    finally:
        sys.path.pop(0)
    return loadgen


def test_loadgen_perturb_until_reverts_mid_run():
    lg = _loadgen()
    patients = [{"Age": 50.0}]
    bodies = lg._Bodies(
        patients, lg.parse_perturb("Age+10"), onset_frac=0.0,
        duration=0.2, until_frac=0.5,
    )
    bodies.arm(time.monotonic())
    assert json.loads(bodies.next_body())["Age"] == 60.0
    time.sleep(0.12)
    assert json.loads(bodies.next_body())["Age"] == 50.0
    desc = bodies.describe()
    assert desc["onset_index"] == 0
    assert desc["revert_index"] == 1
    assert desc["until_fraction"] == 0.5
    assert desc["revert_time_s"] is not None
    # once reverted, it stays reverted
    assert json.loads(bodies.next_body())["Age"] == 50.0


def test_loadgen_revert_file_ends_the_perturbation(tmp_path):
    lg = _loadgen()
    flag = tmp_path / "promoted.flag"
    bodies = lg._Bodies(
        [{"Age": 50.0}], lg.parse_perturb("Age*2"), onset_frac=0.0,
        duration=100.0, revert_file=str(flag),
    )
    bodies.arm(time.monotonic())
    assert json.loads(bodies.next_body())["Age"] == 100.0
    flag.touch()
    time.sleep(bodies.REVERT_POLL_S + 0.05)
    assert json.loads(bodies.next_body())["Age"] == 50.0
    assert bodies.describe()["revert_index"] is not None


# ---------------------------------------------------------------------------
# warm refit + shadow + gate on a real (small) ensemble
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def live_checkpoint(tmp_path_factory):
    """A small fitted StackingParams WITH its own reference profile,
    published as a versioned checkpoint — the continual loop's live
    model."""
    import jax.numpy as jnp

    from machine_learning_replications_tpu.config import (
        ExperimentConfig, GBDTConfig, SVCConfig,
    )
    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.data.schema import (
        selected_indices,
    )
    from machine_learning_replications_tpu.models import pipeline as pl
    from machine_learning_replications_tpu.persist import orbax_io

    X64, y, _ = make_cohort(n=400, seed=7, missing_rate=0.0)
    X17 = np.asarray(X64[:, selected_indices()], np.float64)
    y = np.asarray(y, np.float64)
    cfg = ExperimentConfig(
        gbdt=GBDTConfig(n_estimators=5),
        svc=SVCConfig(platt_cv=2, max_iter=300),
    )
    ens = pl.fit_stacking(X17, y, cfg)
    scores = pl._ensemble_scores(
        ens, X17, chunk_rows=cfg.svc.predict_chunk_rows
    )
    prof = quality.build_reference_profile(X17, scores, y=y)
    live = ens.replace(
        quality={k: jnp.asarray(v) for k, v in prof.items()}
    )
    path = str(tmp_path_factory.mktemp("ck") / "live")
    orbax_io.save_model(path, live)
    return path, X17, cfg


def test_warm_refit_validates_input(live_checkpoint):
    from machine_learning_replications_tpu.learn import retrain
    from machine_learning_replications_tpu.persist import orbax_io

    path, X17, cfg = live_checkpoint
    live = orbax_io.load_model(path)
    with pytest.raises(ValueError, match="min_rows"):
        retrain.warm_refit(live, X17[:10], "/tmp/x", cfg=cfg)
    with pytest.raises(ValueError, match=r"\[n, 17\]"):
        retrain.warm_refit(live, X17[:, :5], "/tmp/x", cfg=cfg)
    bad = X17.copy()
    bad[0, 0] = np.nan
    with pytest.raises(ValueError, match="finite"):
        retrain.warm_refit(live, bad, "/tmp/x", cfg=cfg, min_rows=100)
    with pytest.raises(ValueError, match="labels"):
        retrain.warm_refit(
            live, X17, "/tmp/x", cfg=cfg,
            labels=np.ones(X17.shape[0]), min_rows=100,
        )
    with pytest.raises(ValueError, match="single-class"):
        # a live model that decides every row the same way cannot distill
        class _Constant:
            pass

        import unittest.mock as mock

        with mock.patch.object(
            retrain, "pseudo_labels",
            return_value=np.zeros(X17.shape[0]),
        ):
            retrain.warm_refit(live, X17, "/tmp/x", cfg=cfg,
                               min_rows=100)
    with pytest.raises(TypeError, match="cannot warm-refit"):
        retrain.warm_refit(object(), X17, "/tmp/x", cfg=cfg,
                           min_rows=100)


def test_refit_shadow_gate_arc_on_shifted_cohort(live_checkpoint,
                                                 tmp_path):
    """The loop's core claim, in-process: a warm refit on the shifted
    cohort produces a candidate that (a) carries its own reference
    profile judging the shifted rows ok, (b) passes the shadow gate
    against the live model, and (c) is versioned; while doctored
    thresholds refuse and park the very same candidate."""
    from machine_learning_replications_tpu.learn import retrain
    from machine_learning_replications_tpu.persist import orbax_io

    path, X17, cfg = live_checkpoint
    live = orbax_io.load_model(path)
    shifted = X17.copy()
    shifted[:, 0] += 1.0
    cand_dir = str(tmp_path / "cand")
    cand, info = retrain.warm_refit(
        live, shifted, cand_dir, cfg=cfg, min_rows=200
    )
    assert info["labels_source"] == "distilled"
    assert info["version"] == 1
    assert cand.quality is not None
    verdict = shadowmod.evaluate(
        live, cand, shifted, candidate_version=info["version"]
    )
    assert verdict["pass"], verdict["reasons"]
    stats = verdict["stats"]
    assert stats["rows"] == X17.shape[0]
    assert stats["candidate_quality"]["status"] == "ok"
    # non-trivial divergence: the refit moved with the cohort
    assert stats["divergence_mean"] > 0.0
    # the same candidate under an impossibly strict gate is refused
    strict = shadowmod.ShadowThresholds(max_divergence_mean=0.0)
    refused = shadowmod.evaluate(live, cand, shifted, thresholds=strict)
    assert not refused["pass"]
    promotemod.park(cand_dir, refused)
    assert promotemod.is_parked(cand_dir)
    with pytest.raises(RuntimeError, match="refused"):
        promotemod.publish_candidate(cand_dir, str(tmp_path / "live2"))
    # the candidate checkpoint itself round-trips with its profile
    reloaded = orbax_io.load_model(cand_dir)
    assert sorted(np.asarray(reloaded.quality["bin_counts"]).shape) == \
        sorted(np.asarray(cand.quality["bin_counts"]).shape)


def test_replay_scores_matches_eager_oracle(live_checkpoint):
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.persist import orbax_io

    path, X17, _cfg = live_checkpoint
    live = orbax_io.load_model(path)
    p1, members, rows = shadowmod.replay_scores(live, X17[:64],
                                                chunk_rows=16)
    direct, direct_members = stacking.predict_proba1_with_members(
        live, X17[:64]
    )
    np.testing.assert_array_equal(p1, np.asarray(direct, np.float64))
    np.testing.assert_array_equal(
        members, np.asarray(direct_members, np.float64)
    )
    np.testing.assert_array_equal(rows, X17[:64])


def test_cli_learn_parser_roundtrip():
    from machine_learning_replications_tpu.cli import build_parser

    ap = build_parser()
    args = ap.parse_args([
        "learn", "run", "--model", "/ck", "--capture", "/cap",
        "--router", "http://r", "--alert-streak", "2",
        "--cooldown", "5", "--max-cycles", "1",
    ])
    assert args.role == "run" and args.alert_streak == 2
    args = ap.parse_args([
        "learn", "shadow", "--model", "/ck", "--capture", "/cap",
        "--max-flip-rate", "0.2", "--out", "/tmp/v.json",
    ])
    assert args.role == "shadow" and args.max_flip_rate == 0.2
    # promote applies a verdict — it must not demand the cohort flags
    args = ap.parse_args([
        "learn", "promote", "--model", "/ck", "--router", "http://r",
        "--verdict", "/tmp/v.json",
    ])
    assert args.role == "promote" and args.verdict == "/tmp/v.json"
    args = ap.parse_args(["learn", "status", "--router", "http://r"])
    assert args.role == "status"


def test_obs_report_learn_section(tmp_path):
    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    try:
        import obs_report
    finally:
        sys.path.pop(0)
    j = tmp_path / "j.jsonl"
    events = [
        {"ts": "2026-08-03T10:00:01Z", "kind": "quality_status",
         "from_status": "ok", "to_status": "alert",
         "worst_feature": "Syncope", "worst_psi": 2.3,
         "window_rows": 400},
        {"ts": "2026-08-03T10:00:02Z", "kind": "learn_trigger",
         "fired": True, "reason": "alert", "streak": 3,
         "alert_streak_needed": 3, "worst_feature": "Syncope",
         "worst_psi": 2.3},
        {"ts": "2026-08-03T10:00:03Z", "kind": "learn_retrain_start",
         "rows": 400},
        {"ts": "2026-08-03T10:00:04Z", "kind": "stage_done",
         "stage": "member_gbdt", "seconds": 0.5},
        {"ts": "2026-08-03T10:00:05Z", "kind": "learn_retrain_done",
         "rows": 400, "labels_source": "distilled",
         "family": "StackingParams", "version": 2, "seconds": 4.5},
        {"ts": "2026-08-03T10:00:06Z", "kind": "learn_shadow_verdict",
         "passed": True, "candidate_version": 2, "rows": 400,
         "divergence_mean": 0.12, "divergence_p95": 0.3,
         "divergence_max": 0.4, "flip_rate": 0.03, "score_psi": 1.4,
         "candidate_quality": {"status": "ok", "worst_psi": 0.0,
                               "rows": 400},
         "reasons": []},
        {"ts": "2026-08-03T10:00:07Z", "kind": "learn_promotion",
         "result": "promoted", "candidate": "/c", "version": 3},
        {"ts": "2026-08-03T10:00:08Z", "kind": "quality_status",
         "from_status": "alert", "to_status": "ok",
         "worst_psi": 0.01, "window_rows": 400},
        {"ts": "2026-08-03T10:00:09Z", "kind": "learn_recovery",
         "recovered": True},
    ]
    with open(j, "w") as f:
        for e in events:
            f.write(json.dumps(e) + "\n")
    out = tmp_path / "report.md"
    assert obs_report.main([
        "--learn", "--journal", str(j), "--out", str(out),
    ]) == 0
    text = out.read_text()
    assert "## Continual learning" in text
    assert "ok → alert" in text and "alert → ok" in text
    assert "FIRED" in text
    assert "candidate v2" in text
    assert "shadow verdict: PASS" in text
    assert "promotion promoted" in text
    assert "quality returned to ok" in text
    assert "member_gbdt" in text
