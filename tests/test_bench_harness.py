"""Unit tests for the bench.py orchestrator's pure logic.

The orchestrator itself never imports jax (its design contract), so these
tests import bench.py directly and exercise the probe parser, the
degraded-row plan, and the signal-flush payload — the pieces whose failure
modes produced the r1-r3 driver artifacts (VERDICT r3 missing #1/#4).
"""

from __future__ import annotations

import importlib.util
import json
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


class TestHostCacheTag:
    def test_stable_and_short(self):
        t1, t2 = bench._host_cache_tag(), bench._host_cache_tag()
        assert t1 == t2 and 1 <= len(t1) <= 16

    def test_feature_line_changes_tag(self, tmp_path, monkeypatch):
        """Different CPU feature lines must map to different cache dirs —
        the whole point of the tag (a /tmp surviving a machine-type
        migration must not serve stale AOT executables). Covers both the
        x86 'flags' and aarch64 'Features' spellings."""
        real_open = open

        def fake_cpuinfo(content):
            def _open(path, *a, **k):
                if path == "/proc/cpuinfo":
                    p = tmp_path / "cpuinfo"
                    p.write_text(content)
                    return real_open(p, *a, **k)
                return real_open(path, *a, **k)
            return _open

        import builtins

        monkeypatch.setattr(builtins, "open", fake_cpuinfo("flags\t: a b c\n"))
        t_x86 = bench._host_cache_tag()
        monkeypatch.setattr(builtins, "open", fake_cpuinfo("flags\t: a b d\n"))
        t_x86_other = bench._host_cache_tag()
        monkeypatch.setattr(
            builtins, "open", fake_cpuinfo("Features\t: fp asimd\n")
        )
        t_arm = bench._host_cache_tag()
        assert len({t_x86, t_x86_other, t_arm}) == 3


class TestProbeParser:
    def test_tpu_platform_accepted(self):
        out = "warning: stuff\nPROBE_OK tpu | TPU v5 lite\n"
        assert bench._parse_probe_output(out) == "tpu | TPU v5 lite"

    def test_axon_platform_accepted(self):
        assert bench._parse_probe_output("PROBE_OK axon | TPU v5 lite") is not None

    def test_cpu_platform_rejected(self):
        # VERDICT r3 missing #4: a gracefully-failing plugin yields a
        # healthy CPU backend — that must read as "TPU down", or the
        # harness launches the 10M-row config on single-core CPU jax.
        assert bench._parse_probe_output("PROBE_OK cpu | cpu") is None

    def test_no_probe_line(self):
        assert bench._parse_probe_output("Traceback ...\nRuntimeError: x") is None
        assert bench._parse_probe_output("") is None
        assert bench._parse_probe_output(None) is None

    def test_empty_kind_rejected(self):
        assert bench._parse_probe_output("PROBE_OK") is None
        assert bench._parse_probe_output("PROBE_OK   ") is None


class TestBudgetPlan:
    def test_degraded_rows_shrink_c2_c3(self):
        # r3 post-mortem: 1M-row CPU legs cannot fit the post-probe budget.
        assert bench.DEGRADED_ROWS[2] <= 200_000
        assert bench.DEGRADED_ROWS[3] <= 200_000

    def test_degraded_rows_still_exercise_device_binning(self):
        from machine_learning_replications_tpu.models import gbdt

        assert bench.DEGRADED_ROWS[3] >= gbdt.DEVICE_BINNING_MIN_ROWS

    def test_work_fraction_leaves_emission_margin(self):
        assert bench.WORK_FRACTION <= 0.9
        assert bench.PROBE_FRACTION <= 0.5


class _Args:
    config = None
    rows = None
    budget = 1800
    detail_out = None


class TestManifest:
    """Every BENCH artifact embeds a run manifest (ISSUE 2): provenance on
    the detail payload, a compact digest on the stdout line — and the
    orchestrator stays jax-free building it."""

    def test_payload_carries_manifest(self):
        state = bench._RunState(_Args())
        payload = state.build_payload()
        man = payload["manifest"]
        assert man["kind"] == "manifest"
        assert man["command"] == "bench"
        assert len(man["git_sha"]) == 40
        assert len(man["config_hash"]) == 64
        assert man["run_id"]
        json.dumps(payload)

    def test_manifest_no_jax_in_orchestrator(self):
        # The never-imports-jax contract must survive the manifest import
        # (obs.journal reads versions from importlib.metadata). This test
        # process has jax loaded via conftest, so prove it in a clean
        # subprocess.
        code = (
            "import importlib.util, json, os, sys\n"
            f"repo = {REPO!r}\n"
            "spec = importlib.util.spec_from_file_location("
            "'bench', os.path.join(repo, 'bench.py'))\n"
            "mod = importlib.util.module_from_spec(spec)\n"
            "spec.loader.exec_module(mod)\n"
            "class A:\n"
            "    config = None; rows = None; budget = 60; detail_out = None\n"
            "state = mod._RunState(A())\n"
            "assert 'jax' not in sys.modules, 'manifest pulled jax in'\n"
            "assert state.manifest['git_sha']\n"
            "print('CLEAN')\n"
        )
        out = subprocess.run(
            [sys.executable, "-c", code], cwd=REPO,
            capture_output=True, text=True, timeout=120,
        )
        assert out.returncode == 0, out.stderr
        assert "CLEAN" in out.stdout

    def test_summary_line_carries_compact_manifest(self, tmp_path):
        args = _Args()
        args.detail_out = str(tmp_path / "detail.json")
        state = bench._RunState(args)
        state.results["3"] = {"metric": "m", "value": 1.0, "unit": "s",
                              "vs_baseline": 2.0, "parity_ok": True}
        payload = state.build_payload()
        line = state.summary_line(payload, args.detail_out)
        assert len(line) <= bench.SUMMARY_LINE_CAP
        parsed = json.loads(line)
        man = parsed["manifest"]
        assert man["run_id"] == state.manifest["run_id"]
        assert man["git_sha"] == state.manifest["git_sha"][:12]
        assert man["config_hash"] == state.manifest["config_hash"][:12]


class TestFlushPayload:
    def test_partial_payload_carries_completed_configs(self):
        state = bench._RunState(_Args())
        state.results["3"] = {
            "metric": "gbdt100_train_wall_clock_200000rows", "value": 1.0,
            "unit": "s", "vs_baseline": 12.0, "auc": 0.9, "parity_ok": True,
            "device": "cpu:cpu",
        }
        payload = state.build_payload(partial="flushed on signal 15 (SIGTERM)")
        assert payload["metric"] == "gbdt100_train_wall_clock_200000rows"
        assert payload["vs_baseline"] == 12.0
        assert payload["partial"].startswith("flushed on signal")
        assert payload["parity_ok"] is True
        json.dumps(payload)  # must be serializable as the one stdout line

    def test_empty_payload_is_still_valid_json_line(self):
        state = bench._RunState(_Args())
        payload = state.build_payload(partial="flushed on signal 14 (SIGALRM)")
        assert payload["metric"] == "config3_failed"
        assert payload["value"] == 0.0
        json.dumps(payload)

    def test_emit_is_single_shot(self, capsys, tmp_path):
        args = _Args()
        args.detail_out = str(tmp_path / "detail.json")
        state = bench._RunState(args)
        state.results["3"] = {"metric": "m", "value": 1.0, "unit": "s",
                              "vs_baseline": 2.0, "parity_ok": True}
        rc1 = state.emit()
        rc2 = state.emit()  # second flush (e.g. signal after clean emit): no-op
        out = capsys.readouterr().out
        assert len(out.strip().splitlines()) == 1  # exactly one JSON line
        assert rc1 == 0 and rc2 == 1


def _loaded_state(tmp_path, n_configs=5, err_len=0):
    """A _RunState carrying a realistically fat five-config result set —
    the shape whose full-payload line overflowed the driver's tail window
    in BENCH_r04 (rc 0, ``parsed: null``)."""
    args = _Args()
    args.detail_out = str(tmp_path / "detail.json")
    state = bench._RunState(args)
    for c in range(1, n_configs + 1):
        rec = {
            "metric": f"config{c}_train_wall_clock_1000000rows",
            "value": 1.234567, "unit": "s", "vs_baseline": 93.97,
            "vs_baseline_cold": 2.8, "device": "axon:TPU v5 lite",
            "parity_ok": True, "rows": 1_000_000, "auc": 0.93123456,
            "auc_delta_vs_sklearn": 2.4e-4, "value_cold_s": 27.5,
            "baseline_wall_s": 76.1234, "repeats": 3,
            "phases_s": {f"phase_{i}": 0.123456 for i in range(12)},
            "mfu_pct": 0.021, "hbm_util_pct": 8.9,
            "note": "x" * 120,
        }
        if err_len:
            rec = {"error": "E" * err_len, "tpu_error": "T" * err_len}
        state.results[str(c)] = rec
    for i in range(24):
        state.probe_log.append(
            {"t": "04:00:00", "timeout_s": 300, "outcome": "timeout",
             "wall_s": 300.0}
        )
    return state


class TestSummaryLine:
    """The stdout line must fit the driver's tail/parse window (VERDICT r4
    missing #1 / weak #1): hard cap, contract keys, detail file."""

    def test_five_fat_configs_fit_cap(self, tmp_path):
        state = _loaded_state(tmp_path)
        payload = state.build_payload()
        line = state.summary_line(payload, state.args.detail_out)
        assert len(line) <= bench.SUMMARY_LINE_CAP
        parsed = json.loads(line)
        for key in ("metric", "value", "unit", "vs_baseline"):
            assert key in parsed
        # every config is represented in the digest
        assert set(parsed["configs"]) == {"1", "2", "3", "4", "5"}
        assert parsed["configs"]["3"]["vs_baseline"] == 93.97

    def test_error_storm_still_fits_cap(self, tmp_path):
        # Worst case: every config failed with a long error string (the
        # tunnel-wedge transcript shape). The digest truncates; never drops
        # the contract keys.
        state = _loaded_state(tmp_path, err_len=2000)
        payload = state.build_payload(partial="flushed on signal 15 (SIGTERM)")
        line = state.summary_line(payload, state.args.detail_out)
        assert len(line) <= bench.SUMMARY_LINE_CAP
        parsed = json.loads(line)
        # headline config 3 carries only an error record → build_payload's
        # head.get("metric", ...) default names the failure
        assert parsed["metric"] == "config3_failed"
        assert parsed["config_errors"] == 5

    def test_emit_writes_full_payload_to_detail_file(self, tmp_path, capsys):
        state = _loaded_state(tmp_path)
        rc = state.emit()
        out = capsys.readouterr().out.strip()
        assert rc == 0
        line = out.splitlines()[-1]
        assert len(line) <= bench.SUMMARY_LINE_CAP
        parsed = json.loads(line)
        # outside the repo root → the full path, so the file is findable
        # from the line alone
        assert parsed["detail_file"] == state.args.detail_out
        with open(state.args.detail_out) as f:
            detail = json.load(f)
        # the detail file carries what the stdout line cannot
        assert detail["configs"]["3"]["phases_s"]["phase_0"] == 0.123456
        assert len(detail["probe_log"]) == 24
        assert detail["parity_ok"] is True

    def test_detail_write_failure_still_emits(self, tmp_path, capsys):
        # The contract line prints BEFORE the best-effort detail write, so
        # a wedged filesystem can never gate it; a failed write just means
        # the named file is absent (failure logged to stderr).
        args = _Args()
        # a FILE in the dirname position → makedirs/open raise OSError
        (tmp_path / "blocker").write_text("")
        args.detail_out = str(tmp_path / "blocker" / "detail.json")
        state = bench._RunState(args)
        state.results["3"] = {"metric": "m", "value": 1.0, "unit": "s",
                              "vs_baseline": 2.0, "parity_ok": True}
        rc = state.emit()
        out = capsys.readouterr().out.strip()
        parsed = json.loads(out.splitlines()[-1])
        assert rc == 0
        assert "metric" in parsed and "vs_baseline" in parsed
        assert not os.path.exists(args.detail_out)

    def test_pathological_head_sheds_keys_not_json(self, tmp_path):
        # Even absurdly long head strings must yield VALID JSON ≤ cap —
        # never a mid-token slice of the serialized line.
        args = _Args()
        args.detail_out = str(tmp_path / "detail.json")
        state = bench._RunState(args)
        state.results["3"] = {"metric": "m" * 3000, "value": 1.0, "unit": "s",
                              "vs_baseline": 2.0, "parity_ok": True,
                              "device": "d" * 500}
        payload = state.build_payload(partial="p" * 800)
        line = state.summary_line(payload, args.detail_out)
        assert len(line) <= bench.SUMMARY_LINE_CAP
        parsed = json.loads(line)  # must parse
        assert "value" in parsed and "vs_baseline" in parsed


@pytest.mark.slow
def test_sigterm_mid_run_flushes_partial_json():
    """End-to-end: SIGTERM the orchestrator mid-probe and require the
    stdout JSON line anyway — the exact r3 failure (rc=124, parsed null)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--force-cpu", "--rows", "2000", "--budget", "600"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    import threading
    import time

    try:
        # Wait for the orchestrator's own log lines rather than sleeping a
        # fixed interval: under host load a blind sleep can deliver SIGTERM
        # before the flush handlers are installed (observed flake). The
        # handlers go in at orchestrate() start, strictly before any leg
        # log, so two leg-lines seen on stderr means the handler is live.
        # The reader thread keeps draining stderr afterward so the child
        # never blocks on a full pipe.
        seen = threading.Event()
        count = 0

        def _drain():
            nonlocal count
            for line in proc.stderr:
                count += 1
                if count >= 2:
                    seen.set()

        t = threading.Thread(target=_drain, daemon=True)
        t.start()
        assert seen.wait(timeout=120), "orchestrator produced no log lines"
        time.sleep(1)  # mid-leg, handler installed
        proc.send_signal(signal.SIGTERM)
        # stderr is owned by the drain thread; bound the exit wait, then
        # read stdout (at EOF by then — the flush handler os._exits).
        proc.wait(timeout=60)
        out = proc.stdout.read()
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
    line = out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert "metric" in payload and "vs_baseline" in payload
    assert payload.get("partial", "").startswith("flushed on signal 15")
