"""Unit tests for the bench.py orchestrator's pure logic.

The orchestrator itself never imports jax (its design contract), so these
tests import bench.py directly and exercise the probe parser, the
degraded-row plan, and the signal-flush payload — the pieces whose failure
modes produced the r1-r3 driver artifacts (VERDICT r3 missing #1/#4).
"""

from __future__ import annotations

import importlib.util
import json
import os
import signal
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_bench():
    spec = importlib.util.spec_from_file_location(
        "bench", os.path.join(REPO, "bench.py")
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


class TestHostCacheTag:
    def test_stable_and_short(self):
        t1, t2 = bench._host_cache_tag(), bench._host_cache_tag()
        assert t1 == t2 and 1 <= len(t1) <= 16

    def test_feature_line_changes_tag(self, tmp_path, monkeypatch):
        """Different CPU feature lines must map to different cache dirs —
        the whole point of the tag (a /tmp surviving a machine-type
        migration must not serve stale AOT executables). Covers both the
        x86 'flags' and aarch64 'Features' spellings."""
        real_open = open

        def fake_cpuinfo(content):
            def _open(path, *a, **k):
                if path == "/proc/cpuinfo":
                    p = tmp_path / "cpuinfo"
                    p.write_text(content)
                    return real_open(p, *a, **k)
                return real_open(path, *a, **k)
            return _open

        import builtins

        monkeypatch.setattr(builtins, "open", fake_cpuinfo("flags\t: a b c\n"))
        t_x86 = bench._host_cache_tag()
        monkeypatch.setattr(builtins, "open", fake_cpuinfo("flags\t: a b d\n"))
        t_x86_other = bench._host_cache_tag()
        monkeypatch.setattr(
            builtins, "open", fake_cpuinfo("Features\t: fp asimd\n")
        )
        t_arm = bench._host_cache_tag()
        assert len({t_x86, t_x86_other, t_arm}) == 3


class TestProbeParser:
    def test_tpu_platform_accepted(self):
        out = "warning: stuff\nPROBE_OK tpu | TPU v5 lite\n"
        assert bench._parse_probe_output(out) == "tpu | TPU v5 lite"

    def test_axon_platform_accepted(self):
        assert bench._parse_probe_output("PROBE_OK axon | TPU v5 lite") is not None

    def test_cpu_platform_rejected(self):
        # VERDICT r3 missing #4: a gracefully-failing plugin yields a
        # healthy CPU backend — that must read as "TPU down", or the
        # harness launches the 10M-row config on single-core CPU jax.
        assert bench._parse_probe_output("PROBE_OK cpu | cpu") is None

    def test_no_probe_line(self):
        assert bench._parse_probe_output("Traceback ...\nRuntimeError: x") is None
        assert bench._parse_probe_output("") is None
        assert bench._parse_probe_output(None) is None

    def test_empty_kind_rejected(self):
        assert bench._parse_probe_output("PROBE_OK") is None
        assert bench._parse_probe_output("PROBE_OK   ") is None


class TestBudgetPlan:
    def test_degraded_rows_shrink_c2_c3(self):
        # r3 post-mortem: 1M-row CPU legs cannot fit the post-probe budget.
        assert bench.DEGRADED_ROWS[2] <= 200_000
        assert bench.DEGRADED_ROWS[3] <= 200_000

    def test_degraded_rows_still_exercise_device_binning(self):
        from machine_learning_replications_tpu.models import gbdt

        assert bench.DEGRADED_ROWS[3] >= gbdt.DEVICE_BINNING_MIN_ROWS

    def test_work_fraction_leaves_emission_margin(self):
        assert bench.WORK_FRACTION <= 0.9
        assert bench.PROBE_FRACTION <= 0.5


class _Args:
    config = None
    rows = None
    budget = 1800


class TestFlushPayload:
    def test_partial_payload_carries_completed_configs(self):
        state = bench._RunState(_Args())
        state.results["3"] = {
            "metric": "gbdt100_train_wall_clock_200000rows", "value": 1.0,
            "unit": "s", "vs_baseline": 12.0, "auc": 0.9, "parity_ok": True,
            "device": "cpu:cpu",
        }
        payload = state.build_payload(partial="flushed on signal 15 (SIGTERM)")
        assert payload["metric"] == "gbdt100_train_wall_clock_200000rows"
        assert payload["vs_baseline"] == 12.0
        assert payload["partial"].startswith("flushed on signal")
        assert payload["parity_ok"] is True
        json.dumps(payload)  # must be serializable as the one stdout line

    def test_empty_payload_is_still_valid_json_line(self):
        state = bench._RunState(_Args())
        payload = state.build_payload(partial="flushed on signal 14 (SIGALRM)")
        assert payload["metric"] == "config3_failed"
        assert payload["value"] == 0.0
        json.dumps(payload)

    def test_emit_is_single_shot(self, capsys):
        state = bench._RunState(_Args())
        state.results["3"] = {"metric": "m", "value": 1.0, "unit": "s",
                              "vs_baseline": 2.0, "parity_ok": True}
        rc1 = state.emit()
        rc2 = state.emit()  # second flush (e.g. signal after clean emit): no-op
        out = capsys.readouterr().out
        assert len(out.strip().splitlines()) == 1  # exactly one JSON line
        assert rc1 == 0 and rc2 == 1


@pytest.mark.slow
def test_sigterm_mid_run_flushes_partial_json():
    """End-to-end: SIGTERM the orchestrator mid-probe and require the
    stdout JSON line anyway — the exact r3 failure (rc=124, parsed null)."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--force-cpu", "--rows", "2000", "--budget", "600"],
        cwd=REPO, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
    )
    import threading
    import time

    try:
        # Wait for the orchestrator's own log lines rather than sleeping a
        # fixed interval: under host load a blind sleep can deliver SIGTERM
        # before the flush handlers are installed (observed flake). The
        # handlers go in at orchestrate() start, strictly before any leg
        # log, so two leg-lines seen on stderr means the handler is live.
        # The reader thread keeps draining stderr afterward so the child
        # never blocks on a full pipe.
        seen = threading.Event()
        count = 0

        def _drain():
            nonlocal count
            for line in proc.stderr:
                count += 1
                if count >= 2:
                    seen.set()

        t = threading.Thread(target=_drain, daemon=True)
        t.start()
        assert seen.wait(timeout=120), "orchestrator produced no log lines"
        time.sleep(1)  # mid-leg, handler installed
        proc.send_signal(signal.SIGTERM)
        # stderr is owned by the drain thread; bound the exit wait, then
        # read stdout (at EOF by then — the flush handler os._exits).
        proc.wait(timeout=60)
        out = proc.stdout.read()
    finally:
        if proc.poll() is None:
            proc.kill()
        proc.wait()
    line = out.strip().splitlines()[-1]
    payload = json.loads(line)
    assert "metric" in payload and "vs_baseline" in payload
    assert payload.get("partial", "").startswith("flushed on signal 15")
