"""CV hyperparameter sweep (BASELINE.json config 4) and staged prediction."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import GBDTConfig, SweepConfig
from machine_learning_replications_tpu.data.schema import selected_indices
from machine_learning_replications_tpu.models import gbdt, sweep, tree


def test_staged_prefix_property(cohort_full):
    """staged_proba1 at the full stage count must equal predict_proba1, and
    the m-stage column must equal an independently trained m-stage model
    (boosting stages are prefix-stable)."""
    X, y, _ = cohort_full
    Xs = X[:, selected_indices()]
    full, _ = gbdt.fit(Xs, y, GBDTConfig(n_estimators=20))
    p = sweep.staged_proba1(full, jnp.asarray(Xs), (5, 20))
    np.testing.assert_allclose(
        np.asarray(p[1]), np.asarray(tree.predict_proba1(full, Xs)),
        rtol=1e-12, atol=1e-12,
    )
    short, _ = gbdt.fit(Xs, y, GBDTConfig(n_estimators=5))
    np.testing.assert_allclose(
        np.asarray(p[0]), np.asarray(tree.predict_proba1(short, Xs)),
        rtol=1e-12, atol=1e-12,
    )


def test_cv_sweep_selects_and_refits(cohort_full):
    X, y, _ = cohort_full
    Xs = X[:, selected_indices()]
    cfg = SweepConfig(
        n_estimators_grid=(5, 15), max_depth_grid=(1, 2), cv_folds=3
    )
    res = sweep.cv_sweep(Xs, y, cfg)
    assert res.fold_auc.shape == (2, 2, 3)
    assert res.mean_auc.shape == (2, 2)
    assert 0.5 < res.best_mean_auc <= 1.0
    assert res.best_n_estimators in cfg.n_estimators_grid
    assert res.best_max_depth in cfg.max_depth_grid
    # the selected cell is the argmax of the mean surface
    di = cfg.max_depth_grid.index(res.best_max_depth)
    ei = cfg.n_estimators_grid.index(res.best_n_estimators)
    assert res.mean_auc[di, ei] == res.mean_auc.max()

    params, best_cfg = sweep.refit_best(Xs, y, res)
    assert best_cfg.n_estimators == res.best_n_estimators
    assert params.feature.shape[0] == res.best_n_estimators
    p = tree.predict_proba1(params, Xs)
    assert np.all((np.asarray(p) >= 0) & (np.asarray(p) <= 1))


def test_batched_fold_scoring_matches_per_fold(cohort_full):
    """The one-dispatch-per-depth scoring path (all folds vmapped, padded)
    must reproduce the per-(depth, fold) dispatch path on the unpadded
    rows (tight tolerance, not bitwise: the batched and per-fold programs
    compile separately and XLA may fuse/accumulate differently on TPU)."""
    from machine_learning_replications_tpu.utils.cv import (
        stratified_kfold_test_masks,
    )

    X, y, _ = cohort_full
    Xs = np.asarray(X[:, selected_indices()])
    y = np.asarray(y, dtype=np.float64)
    k, est_grid = 3, (5, 15)
    test_masks = stratified_kfold_test_masks(y, k)
    params = gbdt.fit_folds(
        Xs, y, 1.0 - test_masks, GBDTConfig(n_estimators=15)
    )

    te_idx = [np.flatnonzero(tm > 0.5) for tm in test_masks]
    n_pad = max(len(ix) for ix in te_idx)
    padded = np.stack([np.pad(ix, (0, n_pad - len(ix))) for ix in te_idx])
    batched = np.asarray(
        sweep._staged_allfolds_jit(est_grid)(params, Xs[padded])
    )
    per_fold = sweep._staged_fold_jit(est_grid)
    for kk, ix in enumerate(te_idx):
        np.testing.assert_allclose(
            batched[kk][:, : len(ix)],
            np.asarray(per_fold(params, Xs[ix], kk)),
            rtol=1e-6, atol=1e-7,
        )


def test_sweep_matches_sklearn_gridsearch(cohort_full):
    """Differential vs sklearn GridSearchCV on a small grid: per-cell mean
    CV AUC within the parity budget (±0.005, BASELINE.json)."""
    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.model_selection import GridSearchCV

    X, y, _ = cohort_full
    Xs = np.asarray(X[:, selected_indices()])
    grid = {"n_estimators": [10, 30], "max_depth": [1, 2]}
    gs = GridSearchCV(
        GradientBoostingClassifier(random_state=2020),
        grid,
        scoring="roc_auc",
        cv=3,
    ).fit(Xs, y)
    sk_auc = {
        (p["max_depth"], p["n_estimators"]): m
        for p, m in zip(
            gs.cv_results_["params"], gs.cv_results_["mean_test_score"]
        )
    }
    res = sweep.cv_sweep(
        Xs, y,
        SweepConfig(n_estimators_grid=(10, 30), max_depth_grid=(1, 2), cv_folds=3),
    )
    for di, d in enumerate(res.max_depth_grid):
        for ei, e in enumerate(res.n_estimators_grid):
            assert abs(res.mean_auc[di, ei] - sk_auc[(d, e)]) < 0.005, (d, e)
