"""CV hyperparameter sweep (BASELINE.json config 4) and staged prediction."""

import dataclasses

import jax.numpy as jnp
import numpy as np

from machine_learning_replications_tpu.config import GBDTConfig, SweepConfig
from machine_learning_replications_tpu.data.schema import selected_indices
from machine_learning_replications_tpu.models import gbdt, sweep, tree


def test_staged_prefix_property(cohort_full):
    """staged_proba1 at the full stage count must equal predict_proba1, and
    the m-stage column must equal an independently trained m-stage model
    (boosting stages are prefix-stable)."""
    X, y, _ = cohort_full
    Xs = X[:, selected_indices()]
    full, _ = gbdt.fit(Xs, y, GBDTConfig(n_estimators=20))
    p = sweep.staged_proba1(full, jnp.asarray(Xs), (5, 20))
    np.testing.assert_allclose(
        np.asarray(p[1]), np.asarray(tree.predict_proba1(full, Xs)),
        rtol=1e-12, atol=1e-12,
    )
    short, _ = gbdt.fit(Xs, y, GBDTConfig(n_estimators=5))
    np.testing.assert_allclose(
        np.asarray(p[0]), np.asarray(tree.predict_proba1(short, Xs)),
        rtol=1e-12, atol=1e-12,
    )


def test_cv_sweep_selects_and_refits(cohort_full):
    X, y, _ = cohort_full
    Xs = X[:, selected_indices()]
    cfg = SweepConfig(
        n_estimators_grid=(5, 15), max_depth_grid=(1, 2), cv_folds=3
    )
    res = sweep.cv_sweep(Xs, y, cfg)
    assert res.fold_auc.shape == (2, 2, 3)
    assert res.mean_auc.shape == (2, 2)
    assert 0.5 < res.best_mean_auc <= 1.0
    assert res.best_n_estimators in cfg.n_estimators_grid
    assert res.best_max_depth in cfg.max_depth_grid
    # the selected cell is the argmax of the mean surface
    di = cfg.max_depth_grid.index(res.best_max_depth)
    ei = cfg.n_estimators_grid.index(res.best_n_estimators)
    assert res.mean_auc[di, ei] == res.mean_auc.max()

    params, best_cfg = sweep.refit_best(Xs, y, res)
    assert best_cfg.n_estimators == res.best_n_estimators
    assert params.feature.shape[0] == res.best_n_estimators
    p = tree.predict_proba1(params, Xs)
    assert np.all((np.asarray(p) >= 0) & (np.asarray(p) <= 1))


def test_sweep_matches_sklearn_gridsearch(cohort_full):
    """Differential vs sklearn GridSearchCV on a small grid: per-cell mean
    CV AUC within the parity budget (±0.005, BASELINE.json)."""
    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.model_selection import GridSearchCV

    X, y, _ = cohort_full
    Xs = np.asarray(X[:, selected_indices()])
    grid = {"n_estimators": [10, 30], "max_depth": [1, 2]}
    gs = GridSearchCV(
        GradientBoostingClassifier(random_state=2020),
        grid,
        scoring="roc_auc",
        cv=3,
    ).fit(Xs, y)
    sk_auc = {
        (p["max_depth"], p["n_estimators"]): m
        for p, m in zip(
            gs.cv_results_["params"], gs.cv_results_["mean_test_score"]
        )
    }
    res = sweep.cv_sweep(
        Xs, y,
        SweepConfig(n_estimators_grid=(10, 30), max_depth_grid=(1, 2), cv_folds=3),
    )
    for di, d in enumerate(res.max_depth_grid):
        for ei, e in enumerate(res.n_estimators_grid):
            assert abs(res.mean_auc[di, ei] - sk_auc[(d, e)]) < 0.005, (d, e)
