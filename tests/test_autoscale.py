"""Elastic fleet (ISSUE 11): autoscale policy debounce/cooldown/bounds,
the lifecycle manager's spawn/drain/kill/respawn arcs (fail-closed under
injected faults), the daemon's signal collection off a live router, the
loadgen --ramp schedule, and the obs_report elastic-fleet timeline.

The lifecycle manager is tested with fake clocks, fake processes, and a
recording router client — every arc is deterministic and runs at tick
speed; the real-subprocess integration lives in the surge drill
(``tools/chaos_drill.py --surge``) and its CI job.
"""

import json
import os
import subprocess
import sys
import threading
import time
import urllib.request

import pytest

import machine_learning_replications_tpu.fleet.lifecycle as lifecycle
from machine_learning_replications_tpu.fleet import make_router
from machine_learning_replications_tpu.fleet.autoscale import (
    AUTOSCALE_DECISIONS,
    AutoscaleDaemon,
    AutoscalePolicy,
    AutoscaleThresholds,
)
from machine_learning_replications_tpu.fleet.lifecycle import (
    LIFECYCLE_TRANSITIONS,
    LifecycleManager,
    ReplicaSpec,
)
from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.resilience import faults
from machine_learning_replications_tpu.serve.transport import (
    EventLoopHttpServer,
)

TOOLS = os.path.join(os.path.dirname(__file__), "..", "tools")


# ---------------------------------------------------------------------------
# harness: fake clock/proc/router, journal capture, signal stubs
# ---------------------------------------------------------------------------


@pytest.fixture
def jrn(tmp_path):
    j = journal.RunJournal(tmp_path / "journal.jsonl", command="test")
    journal.set_journal(j)
    yield j
    journal.set_journal(None)
    j.close()


def _events(j, kind=None):
    with open(j.path) as f:
        evs = [json.loads(line) for line in f if line.strip()]
    evs = [e for e in evs if e.get("kind") != "manifest"]
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs


class _FakeProc:
    """A controllable child process: tests decide when it dies and
    whether it honors SIGTERM."""

    _next_pid = [1000]

    def __init__(self, cmd, exits_on_term=True):
        self.cmd = cmd
        self._next_pid[0] += 1
        self.pid = self._next_pid[0]
        self.code = None
        self.terminated = False
        self.killed = False
        self.exits_on_term = exits_on_term

    def poll(self):
        return self.code

    def terminate(self):
        self.terminated = True
        if self.exits_on_term:
            self.code = 0

    def kill(self):
        self.killed = True
        self.code = -9

    def die(self, code=1):
        self.code = code


class _FakeRouter:
    """Recording control-plane client; ``registry_snapshot`` drives the
    manager's zombie detection."""

    def __init__(self):
        self.ops = []
        self.registry_snapshot = []

    def snapshot(self):
        return self.registry_snapshot

    def hold(self, rid):
        self.ops.append(("hold", rid))
        return True

    def release(self, rid):
        self.ops.append(("release", rid))
        return True

    def deregister(self, rid):
        self.ops.append(("deregister", rid))
        return True


def _mk_manager(monkeypatch, clk, ready, depths, launcher=None, **kw):
    """A manager on a fake clock whose readiness probes and drain
    queue-depth reads are table-driven (``ready``: set of ready urls;
    ``depths``: url -> queue depth)."""
    monkeypatch.setattr(
        lifecycle, "probe_replica",
        lambda url, timeout_s=2.0: {
            "ok": url in ready, "ready": url in ready, "version": 1,
        },
    )
    monkeypatch.setattr(
        lifecycle, "replica_queue_depth",
        lambda url, timeout_s=2.0: depths.get(url, 0),
    )
    procs = []

    def default_launcher(cmd):
        proc = _FakeProc(cmd)
        procs.append(proc)
        return proc

    router = _FakeRouter()
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    kw.setdefault("ready_deadline_s", 10.0)
    kw.setdefault("drain_settle_s", 2.0)
    kw.setdefault("term_deadline_s", 5.0)
    kw.setdefault("respawn_backoff_s", 1.0)
    mgr = LifecycleManager(
        ReplicaSpec(model="/ckpt", register_url="http://router"),
        router, launcher=launcher or default_launcher,
        clock=lambda: clk[0], **kw,
    )
    mgr._test_procs = procs
    return mgr, router


def _sig(q=None, lat=None, shed=None, burn=None, alerts=None):
    return {
        "queue_depth": q, "latency_ms": lat, "shed_rate": shed,
        "burn_rate": burn, "alerts_active": alerts,
    }


def _policy(**kw):
    clk = kw.pop("clk", [0.0])
    kw.setdefault("breach_polls", 3)
    kw.setdefault("idle_polls", 3)
    kw.setdefault("cooldown_s", 0.0)
    kw.setdefault("min_replicas", 1)
    kw.setdefault("max_replicas", 4)
    return AutoscalePolicy(clock=lambda: clk[0], **kw), clk


# ---------------------------------------------------------------------------
# policy: debounce, cooldown, bounds
# ---------------------------------------------------------------------------


def test_policy_scale_out_is_debounced(jrn):
    p, _ = _policy()
    assert p.observe(_sig(q=50), desired=2, ready=2) is None
    assert p.observe(_sig(q=50), desired=2, ready=2) is None
    action = p.observe(_sig(q=50), desired=2, ready=2)
    assert action == {
        "decision": "scale_out", "target": 3,
        "reason": "breach: queue_depth",
        "signals": _sig(q=50),
    }
    fired = [
        e for e in _events(jrn, "autoscale_decision") if e.get("decision")
    ]
    assert len(fired) == 1 and fired[0]["target"] == 3
    assert fired[0]["signals"]["queue_depth"] == 50


def test_policy_middle_zone_resets_both_streaks():
    # q=5 sits between the scale-in (1) and scale-out (8) thresholds:
    # neither a breach nor idle — consecutive evidence only.
    p, _ = _policy()
    p.observe(_sig(q=50), 2, 2)
    p.observe(_sig(q=50), 2, 2)
    assert p.observe(_sig(q=5), 2, 2) is None
    assert p.observe(_sig(q=50), 2, 2) is None  # streak restarted at 1
    assert p.observe(_sig(q=50), 2, 2) is None
    assert p.observe(_sig(q=50), 2, 2)["decision"] == "scale_out"


def test_policy_cooldown_suppresses_both_directions():
    p, clk = _policy(cooldown_s=30.0)
    for _ in range(2):
        p.observe(_sig(q=50), 2, 2)
    assert p.observe(_sig(q=50), 2, 2)["decision"] == "scale_out"
    suppressed0 = AUTOSCALE_DECISIONS.labels(
        decision="suppressed_cooldown"
    ).value
    for _ in range(4):
        assert p.observe(_sig(q=50), 3, 3) is None  # cooling down
    assert AUTOSCALE_DECISIONS.labels(
        decision="suppressed_cooldown"
    ).value > suppressed0
    # The quiet tail inside the cooldown cannot scale in either.
    for _ in range(4):
        assert p.observe(_sig(q=0, shed=0.0), 3, 3) is None
    # The idle streak survived the suppressions, so the first poll past
    # the cooldown acts.
    clk[0] = 31.0
    action = p.observe(_sig(q=0, shed=0.0), 3, 3)
    assert action == {
        "decision": "scale_in", "target": 2,
        "reason": "idle: all signals under scale-in thresholds",
        "signals": _sig(q=0, shed=0.0),
    }


def test_policy_bounds_suppression(jrn):
    p, _ = _policy(max_replicas=2)
    at_max0 = AUTOSCALE_DECISIONS.labels(decision="suppressed_at_max").value
    for _ in range(5):
        assert p.observe(_sig(q=50), desired=2, ready=2) is None
    assert AUTOSCALE_DECISIONS.labels(
        decision="suppressed_at_max"
    ).value == at_max0 + 3  # counted each eligible poll...
    suppressed = [
        e for e in _events(jrn, "autoscale_decision")
        if e.get("suppressed_by") == "suppressed_at_max"
    ]
    assert len(suppressed) == 1  # ...journaled once per streak
    at_min0 = AUTOSCALE_DECISIONS.labels(decision="suppressed_at_min").value
    for _ in range(4):
        assert p.observe(_sig(q=0, shed=0.0), desired=1, ready=1) is None
    assert AUTOSCALE_DECISIONS.labels(
        decision="suppressed_at_min"
    ).value > at_min0


def test_policy_scale_in_requires_every_signal_idle():
    p, _ = _policy(idle_polls=2)
    # Queue is quiet but the burn rate sits in the middle zone (above
    # its scale-in twin, below its scale-out threshold): never idle,
    # never scales in.
    for _ in range(6):
        assert p.observe(_sig(q=0, burn=2.0), 2, 2) is None
    assert p.observe(_sig(q=0, burn=0.5), 2, 2) is None
    assert p.observe(_sig(q=0, burn=0.5), 2, 2)["decision"] == "scale_in"


def test_policy_blind_polls_do_not_vote():
    p, _ = _policy(breach_polls=1, idle_polls=1)
    assert p.observe(_sig(), 2, 2) is None  # nothing reachable: no-op


def test_thresholds_validate():
    with pytest.raises(ValueError):
        AutoscaleThresholds(out_queue_depth=2.0, in_queue_depth=5.0)
    with pytest.raises(ValueError):
        AutoscalePolicy(breach_polls=0)
    with pytest.raises(ValueError):
        AutoscalePolicy(min_replicas=3, max_replicas=2)


# ---------------------------------------------------------------------------
# lifecycle manager: spawn → ready → retire → replace arcs
# ---------------------------------------------------------------------------


def test_manager_spawn_to_ready_arc(monkeypatch, jrn):
    clk, ready = [0.0], set()
    mgr, router = _mk_manager(monkeypatch, clk, ready, {})
    mgr.scale_to(1)
    mgr.tick()
    rep = mgr.replicas()[0]
    assert rep["state"] == "spawning" and rep["pid"] is not None
    assert json.dumps(mgr._test_procs[0].cmd).count("--register")
    ready.add(rep["url"])
    clk[0] = 3.0
    mgr.tick()
    assert mgr.replicas()[0]["state"] == "ready"
    spawn = _events(jrn, "lifecycle_spawn")
    assert spawn and not spawn[0]["respawn"]
    assert _events(jrn, "lifecycle_ready")[0]["seconds"] == 3.0
    assert mgr.counts()["ready"] == 1


def test_manager_ready_timeout_fails_closed(monkeypatch, jrn):
    clk, ready = [0.0], set()
    mgr, router = _mk_manager(monkeypatch, clk, ready, {},
                              ready_deadline_s=10.0)
    mgr.scale_to(1)
    mgr.tick()
    proc = mgr._test_procs[0]
    clk[0] = 11.0
    mgr.tick()
    assert proc.killed  # the unready child does not linger
    failed = _events(jrn, "lifecycle_spawn_failed")
    assert failed and "not ready within" in failed[0]["reason"]
    assert ("deregister", "as-1") in router.ops
    assert mgr.replicas()[0]["state"] == "pending"
    # The retry respects the backoff gate, then relaunches.
    mgr.tick()
    assert len(mgr._test_procs) == 1
    clk[0] = 12.5  # past next_spawn_at = 11 + 1s backoff
    mgr.tick()
    assert len(mgr._test_procs) == 2
    ready.add(mgr.replicas()[0]["url"])
    mgr.tick()
    assert mgr.replicas()[0]["state"] == "ready"


def test_manager_crash_detection_respawns_with_backoff(monkeypatch, jrn):
    clk, ready = [0.0], set()
    mgr, router = _mk_manager(monkeypatch, clk, ready, {})
    mgr.scale_to(1)
    mgr.tick()
    ready.add(mgr.replicas()[0]["url"])
    mgr.tick()
    crashes0 = LIFECYCLE_TRANSITIONS.labels(event="crash").value
    mgr._test_procs[0].die(-9)
    clk[0] = 5.0
    mgr.tick()
    assert LIFECYCLE_TRANSITIONS.labels(event="crash").value == crashes0 + 1
    assert ("deregister", "as-1") in router.ops
    assert mgr.replicas()[0]["state"] == "pending"
    mgr.tick()  # inside the backoff window: no respawn yet
    assert len(mgr._test_procs) == 1
    clk[0] = 6.1
    mgr.tick()
    assert len(mgr._test_procs) == 2
    respawn = _events(jrn, "lifecycle_spawn")[-1]
    assert respawn["respawn"] and respawn["replica"] == "as-1"
    mgr.tick()
    assert mgr.replicas()[0]["state"] == "ready"  # same id, same url
    # A second crash doubles the backoff (1 → 2s): attempts were reset
    # by readiness, so this is attempt 1 again at 1s... crash twice
    # WITHOUT an intervening ready to see the doubling.
    mgr._test_procs[-1].die(1)
    ready.clear()
    clk[0] = 10.0
    mgr.tick()
    clk[0] = 11.1
    mgr.tick()  # respawn (attempt 1 after reset: 1s backoff)
    mgr._test_procs[-1].die(1)
    clk[0] = 12.0
    mgr.tick()
    clk[0] = 13.5  # 12 + 2s backoff not yet passed
    mgr.tick()
    n = len(mgr._test_procs)
    clk[0] = 14.1
    mgr.tick()
    assert len(mgr._test_procs) == n + 1


def test_manager_drain_first_retirement_order(monkeypatch, jrn):
    clk, ready, depths = [0.0], set(), {}
    mgr, router = _mk_manager(monkeypatch, clk, ready, depths,
                              drain_settle_s=5.0)
    mgr.scale_to(2)
    mgr.tick()
    for rep in mgr.replicas():
        ready.add(rep["url"])
    mgr.tick()
    assert mgr.counts()["ready"] == 2
    retiring = mgr.replicas()[-1]  # newest leaves first
    depths[retiring["url"]] = 3
    mgr.scale_to(1)
    mgr.tick()
    assert ("hold", retiring["id"]) in router.ops
    assert mgr.get(retiring["id"]).state == "draining"
    proc = mgr._test_procs[1]
    assert not proc.terminated  # in-flight work still draining
    clk[0] = 1.0
    mgr.tick()
    assert not proc.terminated  # queue still has 3 entries
    depths[retiring["url"]] = 0
    clk[0] = 2.0
    mgr.tick()
    assert proc.terminated and not proc.killed
    mgr.tick()
    assert mgr.get(retiring["id"]) is None
    assert ("deregister", retiring["id"]) in router.ops
    kinds = [
        e["kind"] for e in _events(jrn)
        if e.get("replica") == retiring["id"]
        and e["kind"].startswith("lifecycle_")
    ]
    drain_on = kinds[kinds.index("lifecycle_drain"):]
    assert drain_on == ["lifecycle_drain", "lifecycle_term",
                        "lifecycle_exit"]
    assert "lifecycle_kill" not in kinds
    # The hold landed before the SIGTERM: drain-first, provably.
    assert router.ops.index(("hold", retiring["id"])) < \
        router.ops.index(("deregister", retiring["id"]))


def test_manager_stuck_drain_escalates_to_kill(monkeypatch, jrn):
    clk, ready, depths = [0.0], set(), {}
    launcher_procs = []

    def launcher(cmd):
        proc = _FakeProc(cmd, exits_on_term=False)  # ignores SIGTERM
        launcher_procs.append(proc)
        return proc

    mgr, router = _mk_manager(
        monkeypatch, clk, ready, depths, launcher=launcher,
        drain_settle_s=2.0, term_deadline_s=5.0,
    )
    mgr.scale_to(2)
    mgr.tick()
    for rep in mgr.replicas():
        ready.add(rep["url"])
    mgr.tick()
    faults.arm("lifecycle.drain:corrupt@once")
    try:
        retiring = mgr.replicas()[-1]["id"]
        mgr.scale_to(1)
        mgr.tick()  # drain (TERM suppressed by the injected fault)
        clk[0] = 3.0
        mgr.tick()  # settle deadline passed → term step
        term = _events(jrn, "lifecycle_term")[-1]
        assert term["delivered"] is False  # the "replica" ignored it
        proc = launcher_procs[1]
        assert not proc.killed
        clk[0] = 9.0
        mgr.tick()  # term deadline passed → SIGKILL escalation
        assert proc.killed
        kill = _events(jrn, "lifecycle_kill")[-1]
        assert kill["replica"] == retiring
        assert kill["reason"] == "term_deadline"
        mgr.tick()
        assert mgr.get(retiring) is None  # reaped, bounded retirement
    finally:
        faults.reset()


def test_manager_injected_spawn_fault_fails_closed(monkeypatch, jrn):
    clk, ready = [0.0], set()
    mgr, router = _mk_manager(monkeypatch, clk, ready, {})
    faults.arm("lifecycle.spawn:raise@once")
    try:
        mgr.scale_to(1)
        mgr.tick()
        failed = _events(jrn, "lifecycle_spawn_failed")
        assert failed and "injected" in failed[0]["reason"]
        assert not mgr._test_procs  # nothing launched
        clk[0] = 1.5
        mgr.tick()  # the retry (fault was @once) launches for real
        assert len(mgr._test_procs) == 1
    finally:
        faults.reset()


def test_manager_corrupt_spawn_launches_an_unready_replica(monkeypatch):
    clk, ready = [0.0], set()
    mgr, router = _mk_manager(monkeypatch, clk, ready, {})
    faults.arm("lifecycle.spawn:corrupt@once")
    try:
        mgr.scale_to(1)
        mgr.tick()
        # The sabotage is a nonexistent checkpoint: the child would die
        # or never warm — either way the ready-deadline branch owns it.
        assert "/ckpt.__corrupt__" in mgr._test_procs[0].cmd
        clk[0] = 11.0
        mgr.tick()
        assert mgr._test_procs[0].killed
        clk[0] = 12.5
        mgr.tick()
        assert mgr._test_procs[1].cmd.count("/ckpt") and \
            "/ckpt.__corrupt__" not in mgr._test_procs[1].cmd
    finally:
        faults.reset()


def test_manager_registry_zombie_is_replaced(monkeypatch, jrn):
    clk, ready = [0.0], set()
    mgr, router = _mk_manager(monkeypatch, clk, ready, {},
                              unresponsive_probe_fails=4)
    mgr.scale_to(1)
    mgr.tick()
    ready.add(mgr.replicas()[0]["url"])
    mgr.tick()
    proc = mgr._test_procs[0]
    # The process lives, but the registry says it stopped answering.
    router.registry_snapshot = [
        {"id": "as-1", "state": "out", "probe_fails": 6},
    ]
    clk[0] = 5.0
    mgr.tick()
    assert proc.killed
    crash = _events(jrn, "lifecycle_crash")[-1]
    assert "unresponsive" in crash["detail"]
    assert mgr.replicas()[0]["state"] == "pending"


def test_manager_scale_bounds_clamped(monkeypatch):
    clk = [0.0]
    mgr, _ = _mk_manager(monkeypatch, clk, set(), {}, min_replicas=2,
                         max_replicas=3)
    assert mgr.scale_to(99) == 3
    assert mgr.scale_to(0) == 2
    with pytest.raises(ValueError):
        _mk_manager(monkeypatch, clk, set(), {}, min_replicas=0)


def test_manager_scale_in_is_numerically_newest_first(monkeypatch, jrn):
    """Retirement order is creation order, not id-string order: with 10+
    slots "as-10" must retire before "as-9" (lexicographic sort would
    retire the veteran)."""
    class _All:
        def __contains__(self, url):
            return True

    clk = [0.0]
    mgr, _ = _mk_manager(monkeypatch, clk, _All(), {}, min_replicas=1,
                         max_replicas=12)
    mgr.scale_to(10)
    mgr.tick()   # spawn as-1..as-10
    mgr.tick()   # all ready
    assert all(r["state"] == "ready" for r in mgr.replicas())
    mgr.scale_to(9)
    mgr.tick()
    draining = [r["id"] for r in mgr.replicas() if r["state"] == "draining"]
    assert draining == ["as-10"]


def test_manager_repeated_spawn_failure_moves_port(monkeypatch, jrn):
    """A port stolen during the backoff window must not wedge the slot
    forever: after 3 consecutive spawn failures the slot re-allocates a
    fresh port (same id — the registry supports same-id-new-url)."""
    clk = [0.0]

    def bad_launcher(cmd):
        raise OSError("address already in use")

    mgr, _ = _mk_manager(monkeypatch, clk, set(), {},
                         launcher=bad_launcher, min_replicas=1)
    mgr.scale_to(1)
    mgr.tick()                       # attempt 1 fails
    rep = mgr.get("as-1")
    port0 = rep.port
    clk[0] += 2.0
    mgr.tick()                       # attempt 2 fails, port unchanged
    assert rep.attempts == 2 and rep.port == port0
    clk[0] += 3.0
    mgr.tick()                       # attempt 3 fails -> port moves
    assert rep.attempts == 3
    assert rep.port != port0
    assert rep.url.endswith(str(rep.port))


# ---------------------------------------------------------------------------
# daemon signal collection + scaling over a live (stub) fleet
# ---------------------------------------------------------------------------


class _SignalStub:
    """A replica stub with the three surfaces the autoscaler polls."""

    def __init__(self, rid):
        self.rid = rid
        self.queue_depth = 0
        self.burn = 0.5

    def handle_request(self, req, rsp):
        if req.path == "/readyz":
            rsp.send_json(200, {"ready": True, "reasons": [],
                                "replica": self.rid, "version": 1})
        elif req.path == "/healthz":
            rsp.send_json(200, {"status": "ok",
                                "queue_depth": self.queue_depth})
        elif req.path == "/metrics":
            rsp.send_json(200, {
                "runtime": {
                    "slo_burn_rate": {"slo=latency": self.burn},
                },
            })
        elif req.path == "/predict":
            rsp.send_json(200, {"probability": 0.25},
                          headers={"X-Replica": self.rid})
        else:
            rsp.send_json(404, {"error": "nope"})

    def handle_protocol_error(self, exc, rsp):
        rsp.send_json(exc.code, {"error": exc.message}, close=True)


class _CountingManager:
    min_replicas, max_replicas = 1, 4

    def __init__(self):
        self.desired = 2
        self.ticks = 0

    def scale_to(self, n):
        self.desired = n

    def tick(self):
        self.ticks += 1


def _signal_fleet(n=2):
    stubs, httpds, members = [], [], []
    for i in range(n):
        stub = _SignalStub(f"r{i + 1}")
        httpd = EventLoopHttpServer(("127.0.0.1", 0), stub)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        stubs.append(stub)
        httpds.append(httpd)
        members.append(
            (stub.rid, f"http://127.0.0.1:{httpd.server_address[1]}")
        )
    router = make_router(
        port=0, replicas=members, probe_interval_s=0.1,
    ).start_background()
    deadline = time.monotonic() + 10
    while router.registry.ready_count() < n and \
            time.monotonic() < deadline:
        time.sleep(0.02)
    assert router.registry.ready_count() == n
    return router, stubs, httpds, \
        f"http://{router.address[0]}:{router.address[1]}"


def test_daemon_collects_signals_and_scales_live():
    router, stubs, httpds, base = _signal_fleet(2)
    try:
        mgr = _CountingManager()
        daemon = AutoscaleDaemon(
            base, mgr,
            AutoscalePolicy(
                thresholds=AutoscaleThresholds(
                    out_queue_depth=8.0, in_queue_depth=1.0,
                    out_burn_rate=4.0, in_burn_rate=1.0,
                    out_latency_ms=None, in_latency_ms=None,
                ),
                breach_polls=2, idle_polls=3, cooldown_s=0.0,
                min_replicas=1, max_replicas=4,
            ),
        )
        # A couple of routed requests so the router's counters move.
        for _ in range(3):
            req = urllib.request.Request(
                base + "/predict", data=b"{}",
                headers={"Content-Type": "application/json"},
            )
            urllib.request.urlopen(req, timeout=5).read()
        stubs[0].queue_depth = 50
        signals = daemon.collect_signals()
        assert signals["queue_depth"] == 50.0  # max across the fleet
        assert signals["burn_rate"] == 0.5
        assert signals["ready"] == 2
        assert daemon.tick() is None          # breach 1 of 2 (delta prime)
        action = daemon.tick()                # breach 2 of 2 → fire
        assert action["decision"] == "scale_out" and mgr.desired == 3
        assert mgr.ticks >= 2                 # the manager ticks every poll
        stubs[0].queue_depth = 0
        for _ in range(2):
            assert daemon.tick() is None
        action = daemon.tick()
        assert action["decision"] == "scale_in" and mgr.desired == 2
        # shed_rate reads 0.0 from the counter deltas (requests flowed,
        # none shed) — a real reading, required for the idle verdict.
    finally:
        router.shutdown()
        for h in httpds:
            h.server_close()


def test_daemon_survives_unreachable_router():
    mgr = _CountingManager()
    daemon = AutoscaleDaemon("http://127.0.0.1:1", mgr,
                             AutoscalePolicy(), poll_timeout_s=0.2)
    assert daemon.tick() is None  # all-None signals: no decision
    assert daemon.collect_signals()["queue_depth"] is None
    assert mgr.ticks >= 1  # crash detection still runs through a blip


# ---------------------------------------------------------------------------
# loadgen --ramp
# ---------------------------------------------------------------------------


def _loadgen():
    sys.path.insert(0, TOOLS)
    import loadgen

    return loadgen


def test_ramp_schedule_step_and_linear():
    lg = _loadgen()
    sched = lg._RateSchedule.parse("0:1,10:8,30:1")
    assert sched.rate_at(0.0) == 1 and sched.rate_at(9.9) == 1
    assert sched.rate_at(10.0) == 8 and sched.rate_at(29.9) == 8
    assert sched.rate_at(30.0) == 1 and sched.rate_at(999.0) == 1
    lin = lg._RateSchedule.parse("0:2,10:4", shape="linear")
    assert lin.rate_at(5.0) == pytest.approx(3.0)
    assert lin.rate_at(20.0) == 4.0
    desc = sched.describe(connections=16)
    assert desc["spec"] == "0:1,10:8,30:1" and desc["shape"] == "step"
    assert desc["points"][1]["offered_qps"] == 128.0
    for bad in ("5", "0:0", "10:1,5:2", "0:-1"):
        with pytest.raises(ValueError):
            lg._RateSchedule.parse(bad)


def test_loadgen_ramp_artifact_over_live_fleet(tmp_path):
    router, stubs, httpds, base = _signal_fleet(1)
    try:
        out = subprocess.run(
            [sys.executable, os.path.join(TOOLS, "loadgen.py"),
             "--url", base, "--connections", "4",
             "--ramp", "0:5,1:20", "--duration", "2",
             "--out", str(tmp_path / "art.json")],
            capture_output=True, text=True, check=True,
        )
        art = json.loads(out.stdout)
        assert art["n_ok"] > 0 and art["n_err"] == 0
        assert art["ramp"]["spec"] == "0:5,1:20"
        assert art["ramp"]["points"][1]["offered_qps"] == 80.0
        # The burst really ramped: more than the flat-low rate landed.
        assert art["n_ok"] > 5 * 2
    finally:
        router.shutdown()
        for h in httpds:
            h.server_close()


def test_loadgen_ramp_flag_validation():
    lg_path = os.path.join(TOOLS, "loadgen.py")
    for argv in (
        ["--ramp", "0:1"],                                # no --connections
        ["--connections", "2", "--ramp", "0:1",
         "--rate-per-conn", "3"],                         # both pacers
        ["--connections", "2", "--ramp", "nope"],         # bad spec
    ):
        proc = subprocess.run(
            [sys.executable, lg_path, "--duration", "0.1", *argv],
            capture_output=True, text=True,
        )
        assert proc.returncode == 2, (argv, proc.stderr)


# ---------------------------------------------------------------------------
# obs_report: the elastic-fleet timeline
# ---------------------------------------------------------------------------


def test_obs_report_elastic_fleet_timeline(tmp_path):
    journal_path = tmp_path / "autoscale.jsonl"
    events = [
        {"kind": "manifest", "run_id": "x", "ts": "t0",
         "command": "fleet autoscale"},
        {"ts": "t1", "kind": "autoscale_decision", "decision": "scale_out",
         "desired": 2, "ready": 2, "target": 3,
         "reason": "breach: queue_depth",
         "signals": {"queue_depth": 12.0, "latency_ms": 180.2}},
        {"ts": "t2", "kind": "lifecycle_spawn", "replica": "as-3",
         "port": 9000, "attempt": 1, "respawn": False},
        {"ts": "t3", "kind": "fleet_rotation", "replica": "as-3",
         "direction": "in", "reason": "ready probe", "version": 1},
        {"ts": "t4", "kind": "lifecycle_crash", "replica": "as-1",
         "state": "ready", "detail": "process exited -9"},
        {"ts": "t5", "kind": "autoscale_decision", "decision": None,
         "suppressed_by": "cooldown", "reason": "breach: queue_depth",
         "desired": 3, "ready": 2, "target": None,
         "signals": {"queue_depth": 9.0}},
        {"ts": "t6", "kind": "lifecycle_drain", "replica": "as-3",
         "reason": "scale_in", "settle_deadline_s": 8.0},
        {"ts": "t7", "kind": "lifecycle_exit", "replica": "as-3",
         "code": 0, "reason": "scale_in"},
    ]
    journal_path.write_text(
        "".join(json.dumps(e) + "\n" for e in events)
    )
    out = subprocess.run(
        [sys.executable, os.path.join(TOOLS, "obs_report.py"),
         "--fleet", "--journal", str(journal_path)],
        capture_output=True, text=True, check=True,
    )
    text = out.stdout
    assert "## Elastic fleet" in text
    assert "1 fired, 1 suppressed" in text
    assert "scale_out" in text and "queue_depth=12.0" in text
    # One timeline, all three sources joined and time-ordered.
    assert text.index("autoscaler") < text.index("spawn: as-3")
    assert text.index("spawn: as-3") < text.index("rotated in")
    assert text.index("rotated in") < text.index("crash: as-1")
    assert "suppressed by cooldown" in text
    assert "drain: as-3 (scale_in)" in text
