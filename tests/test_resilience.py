"""Resilience layer (resilience/): deterministic fault injection, the
supervised engine (watchdog / circuit breaker / backoff restart), checkpoint
integrity + last-known-good rollback, degraded-mode serving, and graceful
drain under adversity.

The acceptance contract (ISSUE 5): under every injected fault class a
client receives either a correct answer or an explicit shed — never a
wrong answer, never a hang — with every breaker/rollback transition
journaled and exported as ``resilience_*`` / ``fault_injected_total``
metrics that pass the strict exposition validator. ``tools/chaos_drill.py``
drives the same matrix as a standalone artifact-producing drill; these
tests pin the semantics piece by piece, CPU-only, under the tier-1 marker
set.
"""

import json
import os
import signal
import socket
import threading
import time
import urllib.error
import urllib.request
import warnings

import numpy as np
import pytest

from machine_learning_replications_tpu.data.examples import (
    EXAMPLE_PATIENT,
    patient_row,
)
from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.resilience import faults
from machine_learning_replications_tpu.resilience import lastgood
from machine_learning_replications_tpu.resilience.supervisor import (
    BreakerOpen,
    ComputeDeadlineExceeded,
    SupervisedEngine,
)
from machine_learning_replications_tpu.serve import make_server


@pytest.fixture(autouse=True)
def _clean_faults():
    """The fault registry is process-global by design; tests must not leak
    armed sites into each other (tier-1 runs with -p no:randomly, but the
    hygiene must not depend on it)."""
    faults.reset()
    yield
    faults.reset()


@pytest.fixture()
def run_journal(tmp_path):
    """An active journal for the duration of one test; yields its path."""
    jrn = journal.RunJournal(tmp_path / "journal.jsonl", command="test")
    journal.set_journal(jrn)
    yield jrn.path
    journal.set_journal(None)
    jrn.close()


def _events(path, kind=None):
    with open(path) as f:
        evs = [json.loads(line) for line in f]
    return [e for e in evs if kind is None or e.get("kind") == kind]


@pytest.fixture(scope="module")
def stacking_params():
    """Tiny sklearn-imported stacking ensemble (same import route as the
    shipped pickle; small enough to warm in a couple of seconds)."""
    from sklearn.ensemble import (
        GradientBoostingClassifier, StackingClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    from machine_learning_replications_tpu.persist import import_stacking

    rng = np.random.default_rng(5)
    X = rng.normal(size=(80, 17))
    y = (X @ rng.normal(size=17) > 0).astype(float)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = StackingClassifier(
            estimators=[
                ("svc", make_pipeline(
                    StandardScaler(), SVC(probability=True, random_state=0))),
                ("gbc", GradientBoostingClassifier(
                    n_estimators=3, max_depth=1, random_state=0)),
                ("lg", LogisticRegression()),
            ],
            final_estimator=LogisticRegression(),
        ).fit(X, y)
    return import_stacking(clf)


# ---------------------------------------------------------------------------
# faults: spec grammar, schedules, registry semantics
# ---------------------------------------------------------------------------


def test_spec_grammar_roundtrip():
    for text in (
        "engine.compute:raise",
        "engine.compute:raise@n=3",
        "batcher.flush:delay=0.5@p=0.25,seed=7",
        "persist.restore:corrupt@once",
        "persist.save:corrupt@count=2",
    ):
        spec = faults.parse_spec(text)
        # describe() is the canonical rendering; re-parsing it must be a
        # fixed point (the journal records describe() strings).
        again = faults.parse_spec(spec.describe())
        assert again.describe() == spec.describe()


@pytest.mark.parametrize("bad", [
    "engine.compute",                 # no mode
    "nosuch.site:raise",              # unknown site
    "engine.compute:corrupt",         # corrupt unsupported at this site
    "engine.compute:delay",           # delay without seconds
    "engine.compute:raise=5",         # raise takes no arg
    "engine.compute:raise@n=0",       # nth < 1
    "engine.compute:raise@p=1.5",     # p out of range
    "engine.compute:raise@n=2,p=0.5",  # n and p exclusive
    "engine.compute:raise@bogus=1",   # unknown option
])
def test_spec_grammar_rejects(bad):
    with pytest.raises(ValueError):
        faults.parse_spec(bad)


def test_fire_unarmed_is_noop_and_returns_false():
    assert faults.fire("engine.compute") is False
    assert faults.snapshot()["armed"] == {}


def test_raise_schedule_nth_fires_exactly_once():
    faults.arm("engine.compute:raise@n=3")
    faults.fire("engine.compute")
    faults.fire("engine.compute")
    with pytest.raises(faults.InjectedFault):
        faults.fire("engine.compute")
    # @n self-disarms after its single firing: call 4+ is clean
    assert faults.fire("engine.compute") is False
    assert faults.snapshot()["armed"] == {}


def test_count_schedule_and_snapshot_counts():
    faults.arm("engine.compute:raise@count=2")
    for _ in range(2):
        with pytest.raises(faults.InjectedFault):
            faults.fire("engine.compute")
    assert faults.fire("engine.compute") is False
    faults.arm("batcher.flush:delay=0.001")
    faults.fire("batcher.flush")
    snap = faults.snapshot()
    assert snap["armed"]["batcher.flush"]["fires"] == 1
    assert faults.disarm("batcher.flush") is True
    assert faults.disarm("batcher.flush") is False


def test_probability_schedule_is_seed_deterministic():
    def firing_pattern(seed, n=40):
        faults.arm(f"persist.restore:corrupt@p=0.5,seed={seed}")
        pattern = [faults.fire("persist.restore") for _ in range(n)]
        faults.reset()
        return pattern

    a, b = firing_pattern(7), firing_pattern(7)
    assert a == b, "same seed must replay the same schedule"
    assert any(a) and not all(a)
    assert firing_pattern(8) != a  # and the seed actually matters


def test_firing_is_journaled_and_counted(run_journal):
    before = faults.FAULTS_INJECTED.labels(site="engine.warmup").value
    faults.arm("engine.warmup:raise@once")
    with pytest.raises(faults.InjectedFault):
        faults.fire("engine.warmup")
    assert faults.FAULTS_INJECTED.labels(
        site="engine.warmup").value == before + 1
    fired = _events(run_journal, "fault_injected")
    assert fired and fired[-1]["site"] == "engine.warmup"
    assert _events(run_journal, "fault_armed")


# ---------------------------------------------------------------------------
# supervisor: watchdog, breaker, restart, quality re-enable
# ---------------------------------------------------------------------------


class _ScriptedEngine:
    """Engine double whose predict follows a script of 'ok' | 'fail' |
     'wedge' actions (repeating the last action when exhausted)."""

    def __init__(self, script, quality=None):
        self.script = list(script)
        self.calls = 0
        self.quality = quality
        self.params = object()
        self.buckets = (1, 8)
        self.warm = True
        self.n_features = 17
        self.trace_counts = {}

    def predict(self, X):
        action = self.script[min(self.calls, len(self.script) - 1)]
        self.calls += 1
        if action == "fail":
            raise RuntimeError("scripted failure")
        if action == "wedge":
            time.sleep(3.0)
        return np.asarray(X).mean(axis=1)

    def bucket_for(self, n):
        return 8

    def compile_count(self):
        return 0

    def warmup(self, say=None):
        return {}


def _supervised(script, factory_script=("ok",), **kw):
    made = []

    def factory():
        eng = _ScriptedEngine(factory_script)
        made.append(eng)
        return eng

    sup = SupervisedEngine(
        _ScriptedEngine(script), factory,
        flush_deadline_s=kw.pop("flush_deadline_s", 1.0),
        breaker_failures=kw.pop("breaker_failures", 2),
        restart_backoff_s=kw.pop("restart_backoff_s", 0.05),
        restart_backoff_max_s=kw.pop("restart_backoff_max_s", 0.2),
        **kw,
    )
    return sup, made


def _wait(pred, timeout_s=10.0, what="condition"):
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_predict_tagged_version_follows_the_computing_engine():
    # The fleet-identity contract (docs/FLEET.md): the version paired
    # with the probabilities is the one of the engine that COMPUTED
    # them, captured under the swap lock — not ambient handle state.
    eng1 = _ScriptedEngine(["ok"])
    eng1.model_version = 1
    sup = SupervisedEngine(eng1, lambda: eng1, flush_deadline_s=1.0)
    X = np.ones((2, 17))
    out, version = sup.predict_tagged(X)
    assert version == 1 and out.shape == (2,)
    eng2 = _ScriptedEngine(["ok"])
    eng2.model_version = 2
    sup.swap_engine(eng2)
    _, version = sup.predict_tagged(X)
    assert version == 2
    # plain predict keeps its bare-probabilities contract
    assert sup.predict(X).shape == (2,)
    sup.close()


def test_breaker_opens_after_consecutive_failures_then_recovers(run_journal):
    sup, made = _supervised(["fail"])
    X = np.zeros((2, 17))
    for _ in range(2):
        with pytest.raises(RuntimeError, match="scripted"):
            sup.predict(X)
    assert sup.breaker_open
    snap = sup.snapshot()
    assert snap["state"] == "open" and "consecutive" in snap["open_reason"]
    # While open: instant explicit shed, with a positive Retry-After
    with pytest.raises(BreakerOpen):
        sup.predict(X)
    assert sup.retry_after_s() >= 1.0
    # The restarter swaps in the factory's healthy engine and closes
    _wait(lambda: not sup.breaker_open, what="breaker close")
    assert made, "factory was never called"
    out = sup.predict(X)
    assert out.shape == (2,)
    kinds = [e["kind"] for e in _events(run_journal)]
    assert "breaker_open" in kinds and "breaker_close" in kinds
    restarts = _events(run_journal, "engine_restart")
    assert restarts and restarts[-1]["ok"] is True
    sup.close()


def test_single_failure_below_threshold_does_not_trip():
    sup, _ = _supervised(["fail", "ok"], breaker_failures=2)
    X = np.zeros((1, 17))
    with pytest.raises(RuntimeError):
        sup.predict(X)
    assert not sup.breaker_open
    # success resets the streak; a later single failure still doesn't trip
    sup.predict(X)
    assert sup.snapshot()["fail_streak"] == 0
    sup.close()


def test_watchdog_abandons_wedged_compute_in_bounded_time(run_journal):
    sup, _ = _supervised(["wedge"], flush_deadline_s=0.2)
    t0 = time.monotonic()
    with pytest.raises(ComputeDeadlineExceeded):
        sup.predict(np.zeros((1, 17)))
    elapsed = time.monotonic() - t0
    # Explicit failure at the deadline, NOT after the 3 s injected wedge
    assert elapsed < 1.5, f"watchdog took {elapsed:.2f}s"
    assert sup.breaker_open
    opened = _events(run_journal, "breaker_open")
    assert opened and opened[-1]["wedged"] is True
    _wait(lambda: not sup.breaker_open, what="recovery after wedge")
    assert sup.predict(np.zeros((1, 17))).shape == (1,)
    sup.close()


def test_restart_retries_failing_factory_with_bounded_backoff(run_journal):
    attempts = []

    def flaky_factory():
        attempts.append(time.monotonic())
        if len(attempts) < 3:
            raise RuntimeError("warmup failed (injected)")
        return _ScriptedEngine(["ok"])

    sup = SupervisedEngine(
        _ScriptedEngine(["fail"]), flaky_factory,
        breaker_failures=1, restart_backoff_s=0.05,
        restart_backoff_max_s=0.2,
    )
    with pytest.raises(RuntimeError):
        sup.predict(np.zeros((1, 17)))
    _wait(lambda: not sup.breaker_open, what="recovery after flaky factory")
    assert len(attempts) == 3
    failed = [
        e for e in _events(run_journal, "engine_restart") if not e["ok"]
    ]
    assert len(failed) == 2
    # Exponential spacing: the second retry gap is larger than the first
    # (bounded by the cap; generous slack for scheduler jitter).
    assert attempts[2] - attempts[1] > (attempts[1] - attempts[0]) * 0.9
    sup.close()


def test_quality_feed_reenabled_after_successful_restart(run_journal):
    from machine_learning_replications_tpu.obs import quality

    rng = np.random.default_rng(0)
    X = rng.normal(size=(300, 4))
    scores = rng.uniform(size=300)
    profile = quality.build_reference_profile(
        X, scores, (scores > 0.5).astype(float)
    )
    from machine_learning_replications_tpu.obs.registry import (
        MetricsRegistry,
    )

    monitor = quality.QualityMonitor(
        profile, window=256, registry=MetricsRegistry()
    )
    monitor.disable("feed quarantined: scripted")
    assert monitor.health()["status"] == "disabled"

    sup, _ = _supervised(["fail"], breaker_failures=1)
    # The factory's replacement engine carries the (disabled) monitor —
    # exactly what make_server's rebuild closure does.
    sup._factory = lambda: _ScriptedEngine(["ok"], quality=monitor)
    with pytest.raises(RuntimeError):
        sup.predict(np.zeros((1, 17)))
    _wait(lambda: not sup.breaker_open, what="restart with quality monitor")
    assert monitor.health()["status"] != "disabled"
    reen = _events(run_journal, "quality_feed_reenabled")
    assert reen and reen[-1]["after"] == "engine_restart"
    # Idempotence: an enabled monitor reports False, no double journal
    assert monitor.reenable() is False
    sup.close()


def test_close_stops_an_inflight_restarter():
    """A supervisor shut down while the breaker is open must stop
    rebuilding: without the closed flag, the restarter would re-warm
    engines every backoff interval for the process lifetime."""
    attempts = []

    def always_failing_factory():
        attempts.append(time.monotonic())
        raise RuntimeError("still broken")

    sup = SupervisedEngine(
        _ScriptedEngine(["fail"]), always_failing_factory,
        breaker_failures=1, restart_backoff_s=0.05,
        restart_backoff_max_s=0.05,
    )
    with pytest.raises(RuntimeError):
        sup.predict(np.zeros((1, 17)))
    _wait(lambda: len(attempts) >= 2, what="restarter spinning")
    sup.close()
    time.sleep(0.3)
    n = len(attempts)
    time.sleep(0.3)
    assert len(attempts) == n, "restarter kept rebuilding after close()"
    assert sup.breaker_open  # closed-while-degraded stays degraded


def test_second_supervisor_does_not_mask_open_breaker_gauge():
    """The breaker-state gauge is process-global; constructing another
    supervisor (multi-server-per-process, the test suite's own pattern)
    must not publish a phantom 'closed' over a degraded server."""
    from machine_learning_replications_tpu.resilience.supervisor import (
        BREAKER_STATE,
    )

    def dead_factory():
        raise RuntimeError("never recovers")

    sup1 = SupervisedEngine(
        _ScriptedEngine(["fail"]), dead_factory, breaker_failures=1,
        restart_backoff_s=0.05, restart_backoff_max_s=0.05,
    )
    with pytest.raises(RuntimeError):
        sup1.predict(np.zeros((1, 17)))
    assert sup1.breaker_open and BREAKER_STATE.get().value == 1.0
    sup2, _ = _supervised(["ok"])
    assert BREAKER_STATE.get().value == 1.0, \
        "second supervisor's construction masked the open breaker"
    sup1.close()
    sup2.close()
    BREAKER_STATE.get().set(0.0)  # restore for later tests


def test_inflight_breaker_shed_counts_as_shed_not_engine_error():
    """Requests admitted just before the breaker opened are SHED when
    their flush hits BreakerOpen — serve_shed_total, not
    serve_errors_total (the engine was never invoked)."""
    from machine_learning_replications_tpu.serve import (
        MicroBatcher, ServingMetrics,
    )

    class _OpenEngine:
        n_features = 17

        def predict(self, X):
            raise BreakerOpen(1.0)

    m = ServingMetrics()
    b = MicroBatcher(_OpenEngine(), max_batch_size=4, max_wait_ms=1.0,
                     max_queue=16, metrics=m)
    try:
        futs = [b.submit(np.zeros(17)) for _ in range(3)]
        for f in futs:
            with pytest.raises(BreakerOpen):
                f.result(timeout=5.0)
        assert m.shed_total.value == 3
        assert m.errors_total.value == 0
    finally:
        b.close(drain=False)


def test_supervisor_parameter_validation():
    eng = _ScriptedEngine(["ok"])
    with pytest.raises(ValueError):
        SupervisedEngine(eng, lambda: eng, flush_deadline_s=0)
    with pytest.raises(ValueError):
        SupervisedEngine(eng, lambda: eng, breaker_failures=0)
    with pytest.raises(ValueError):
        SupervisedEngine(
            eng, lambda: eng, restart_backoff_s=2.0,
            restart_backoff_max_s=1.0,
        )


# ---------------------------------------------------------------------------
# checkpoint integrity + last-known-good rollback
# ---------------------------------------------------------------------------


def test_save_publishes_integrity_manifest(tmp_path, stacking_params):
    from machine_learning_replications_tpu.persist import orbax_io

    path = tmp_path / "ckpt"
    orbax_io.save_model(path, stacking_params)
    manifest = json.loads((path / "integrity.json").read_text())
    assert manifest["format"] == 1 and manifest["files"]
    # The sidecar template is covered too (it is part of the restore path)
    assert "pytree_template.json" in manifest["files"]
    assert orbax_io.verify_checkpoint(path) is True
    # Manifest-less (legacy) checkpoints are tolerated, not verified
    (path / "integrity.json").unlink()
    assert orbax_io.verify_checkpoint(path) is False
    assert orbax_io.load_model(path) is not None


def test_corruption_detected_before_orbax_touches_it(tmp_path,
                                                     stacking_params):
    from machine_learning_replications_tpu.persist import orbax_io

    path = tmp_path / "ckpt"
    orbax_io.save_model(path, stacking_params)
    # Flip one byte of the largest payload file
    orbax_io._corrupt_payload(str(path))
    with pytest.raises(orbax_io.CheckpointIntegrityError):
        orbax_io.load_model(path)  # no lastgood retained -> loud failure


def test_corrupt_primary_rolls_back_to_lastgood(tmp_path, run_journal,
                                                stacking_params):
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.persist import orbax_io

    v1 = stacking_params
    # A distinguishable v2: perturb the meta coefficients
    v2 = v1.replace(meta=v1.meta.replace(
        coef=np.asarray(v1.meta.coef) * 1.5
    ))
    p1 = float(np.asarray(stacking.predict_proba1(v1, patient_row()))[0])
    p2 = float(np.asarray(stacking.predict_proba1(v2, patient_row()))[0])
    assert p1 != p2

    path = tmp_path / "model"
    orbax_io.save_model(path, v1)
    orbax_io.save_model(path, v2)  # v1 rotated to lastgood
    assert os.path.isdir(lastgood.lastgood_path(path))
    before = lastgood.CHECKPOINT_ROLLBACKS.get().value
    orbax_io._corrupt_payload(str(path))
    restored = orbax_io.load_model(path)
    # The bad deploy degrades to the PREVIOUS model, exactly
    got = float(np.asarray(
        stacking.predict_proba1(restored, patient_row()))[0])
    assert got == p1
    assert lastgood.CHECKPOINT_ROLLBACKS.get().value == before + 1
    rb = _events(run_journal, "checkpoint_rollback")
    assert rb and rb[-1]["path"] == str(path)
    assert "CheckpointIntegrityError" in rb[-1]["error"]


def test_rotten_primary_is_not_rotated_over_good_lastgood(
    tmp_path, run_journal, stacking_params
):
    """A primary that rotted on disk AFTER publish must not replace a
    genuinely good last-known-good at the next save — that would destroy
    the rollback net exactly when it is about to be needed. The per-save
    guard is shallow (size-only — re-hashing the whole previous
    checkpoint every save would triple checkpoint I/O), so the rot here
    is a truncation; same-size bit rot is caught by the deep verify every
    restore runs."""
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.persist import orbax_io

    v1 = stacking_params
    v2 = v1.replace(meta=v1.meta.replace(
        coef=np.asarray(v1.meta.coef) * 1.5
    ))
    p1 = float(np.asarray(stacking.predict_proba1(v1, patient_row()))[0])

    path = tmp_path / "model"
    orbax_io.save_model(path, v1)
    orbax_io.save_model(path, v2)            # lastgood = v1 (good)
    # Truncate the largest payload file: the primary v2 rots on disk
    biggest = max(
        (os.path.join(path, rel) for rel in orbax_io._payload_files(path)),
        key=os.path.getsize,
    )
    with open(biggest, "r+b") as f:
        f.truncate(max(os.path.getsize(biggest) // 2, 1))
    orbax_io.save_model(path, v2)            # retain must SKIP the rot
    skipped = _events(run_journal, "checkpoint_retain_skipped")
    assert skipped and "CheckpointIntegrityError" in skipped[-1]["error"]
    # The lastgood slot still holds good v1, not the corrupt v2
    lg = orbax_io.load_model(lastgood.lastgood_path(path))
    got = float(np.asarray(stacking.predict_proba1(lg, patient_row()))[0])
    assert got == p1


def test_loadgen_retries_rejected_in_open_loop(capsys):
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                     "tools"))
    try:
        import loadgen
    finally:
        _sys.path.pop(0)
    with pytest.raises(SystemExit):
        loadgen.main(["--mode", "open", "--retries", "2"])
    assert "open loop" in capsys.readouterr().err


def test_interrupted_save_leaves_previous_checkpoint_intact(
    tmp_path, stacking_params
):
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.persist import orbax_io

    path = tmp_path / "model"
    orbax_io.save_model(path, stacking_params)
    p_before = float(np.asarray(
        stacking.predict_proba1(stacking_params, patient_row()))[0])
    faults.arm("persist.save:raise@once")
    with pytest.raises(faults.InjectedFault):
        orbax_io.save_model(path, stacking_params)
    # The torn publish left no tmp litter and the old checkpoint loads
    assert not [d for d in os.listdir(tmp_path) if ".tmp." in d]
    restored = orbax_io.load_model(path)
    got = float(np.asarray(
        stacking.predict_proba1(restored, patient_row()))[0])
    assert got == p_before


def test_corrupt_at_save_detected_at_restore(tmp_path, stacking_params):
    from machine_learning_replications_tpu.persist import orbax_io

    path = tmp_path / "model"
    faults.arm("persist.save:corrupt@once")
    orbax_io.save_model(path, stacking_params)  # bytes torn AFTER checksum
    with pytest.raises(orbax_io.CheckpointIntegrityError):
        orbax_io.load_model(path)


# ---------------------------------------------------------------------------
# degraded-mode serving over live HTTP
# ---------------------------------------------------------------------------


def _post(url, obj, timeout=10.0):
    req = urllib.request.Request(
        url, data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, json.loads(resp.read()), dict(resp.headers)


def _get(url, timeout=10.0):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read())
    except urllib.error.HTTPError as exc:
        return exc.code, json.loads(exc.read() or b"{}")


@pytest.fixture()
def chaos_server(stacking_params):
    handle = make_server(
        stacking_params, port=0, buckets=(1, 8), max_wait_ms=1.0,
        flush_deadline_s=0.5, breaker_failures=2,
        restart_backoff_s=0.1, restart_backoff_max_s=0.5,
    ).start_background()
    host, port = handle.address
    yield handle, f"http://{host}:{port}"
    handle.shutdown()


def test_degraded_mode_sheds_503_with_retry_after_then_recovers(
    chaos_server, run_journal
):
    handle, url = chaos_server
    status, body, _ = _post(url + "/predict", dict(EXAMPLE_PATIENT))
    golden = body["probability"]

    faults.arm("engine.compute:raise")
    saw_503_headers = None
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        try:
            _post(url + "/predict", dict(EXAMPLE_PATIENT))
        except urllib.error.HTTPError as exc:
            exc.read()
            if exc.code == 503:
                saw_503_headers = dict(exc.headers)
                break
            assert exc.code == 500  # pre-breaker failures are explicit
    assert saw_503_headers is not None, "breaker never opened"
    ra = saw_503_headers.get("Retry-After")
    assert ra is not None and int(ra) >= 1

    # Degraded is visible everywhere an orchestrator looks: liveness 200
    # with status=degraded, readiness 503 naming the breaker.
    status, health = _get(url + "/healthz")
    assert status == 200 and health["status"] == "degraded"
    assert health["ready"] is False
    assert health["breaker"]["state"] == "open"
    status, ready = _get(url + "/readyz")
    assert status == 503 and "degraded: circuit breaker open" in \
        ready["reasons"]

    faults.reset()
    deadline = time.monotonic() + 15.0
    recovered = False
    while time.monotonic() < deadline:
        try:
            status, body, _ = _post(url + "/predict", dict(EXAMPLE_PATIENT))
            assert body["probability"] == golden  # never a wrong answer
            recovered = True
            break
        except urllib.error.HTTPError as exc:
            exc.read()
            time.sleep(0.05)
    assert recovered, "server never recovered after disarm"
    status, health = _get(url + "/healthz")
    assert health["status"] == "ok" and health["ready"] is True

    kinds = [e["kind"] for e in _events(run_journal)]
    assert "breaker_open" in kinds and "breaker_close" in kinds
    assert "fault_injected" in kinds
    sheds = [
        e for e in _events(run_journal) if e.get("kind") == "breaker_open"
    ]
    assert sheds


def test_wedged_flush_is_abandoned_not_hung(chaos_server):
    handle, url = chaos_server
    faults.arm("engine.compute:delay=3.0@n=1")
    t0 = time.monotonic()
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url + "/predict", dict(EXAMPLE_PATIENT))
    ei.value.read()
    elapsed = time.monotonic() - t0
    # 504 at the 0.5 s flush deadline — bounded, NOT the 3 s wedge
    assert ei.value.code in (503, 504)
    assert elapsed < 2.5, f"client waited {elapsed:.2f}s"
    # and the server recovers without a process restart
    deadline = time.monotonic() + 15.0
    while time.monotonic() < deadline:
        try:
            status, _, _ = _post(url + "/predict", dict(EXAMPLE_PATIENT))
            assert status == 200
            return
        except urllib.error.HTTPError as exc:
            exc.read()
            time.sleep(0.05)
    raise AssertionError("no recovery after wedge")


def test_resilience_families_on_metrics_pass_strict_validator(chaos_server):
    import sys as _sys

    _sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                     "tools"))
    try:
        import validate_metrics
    finally:
        _sys.path.pop(0)
    handle, url = chaos_server
    _post(url + "/predict", dict(EXAMPLE_PATIENT))
    with urllib.request.urlopen(url + "/metrics", timeout=10.0) as resp:
        page = resp.read().decode()
    for family in ("fault_injected_total", "resilience_breaker_state",
                   "resilience_breaker_transitions_total",
                   "resilience_engine_restarts_total",
                   "resilience_watchdog_trips_total",
                   "resilience_degraded_sheds_total",
                   "resilience_checkpoint_rollbacks_total"):
        assert family in page, f"{family} missing"
    assert validate_metrics.validate(page) == []


def test_debug_faults_endpoint_guard_and_control(chaos_server, monkeypatch):
    handle, url = chaos_server
    # Guard: without the opt-in, both methods 403 and nothing arms
    monkeypatch.setattr(faults, "_endpoint_enabled", False)
    status, body = _get(url + "/debug/faults")
    assert status == 403
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url + "/debug/faults", {"arm": "engine.compute:raise"})
    assert ei.value.code == 403
    ei.value.read()
    assert faults.snapshot()["armed"] == {}

    monkeypatch.setattr(faults, "_endpoint_enabled", True)
    status, snap, _ = _post(
        url + "/debug/faults", {"arm": "batcher.flush:delay=0.001@once"}
    )
    assert status == 200 and "batcher.flush" in snap["armed"]
    status, body = _get(url + "/debug/faults")
    assert status == 200 and "batcher.flush" in body["armed"]
    status, snap, _ = _post(url + "/debug/faults",
                            {"disarm": "batcher.flush"})
    assert snap["armed"] == {}
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(url + "/debug/faults", {"arm": "nosuch.site:raise"})
    assert ei.value.code == 400
    ei.value.read()


def test_readyz_tracks_warmup_drain_and_liveness_split(stacking_params):
    handle = make_server(
        stacking_params, port=0, buckets=(1,), warmup=False,
    ).start_background()
    try:
        host, port = handle.address
        url = f"http://{host}:{port}"
        # Cold engine: alive (healthz 200, status ok) but NOT ready
        status, health = _get(url + "/healthz")
        assert status == 200 and health["status"] == "ok"
        assert health["ready"] is False
        status, ready = _get(url + "/readyz")
        assert status == 503 and "warmup incomplete" in ready["reasons"]

        handle.engine.warmup()
        status, ready = _get(url + "/readyz")
        assert status == 200 and ready["ready"] is True

        # Draining: readiness drops first so the LB rotates us out while
        # in-flight work completes
        handle.draining = True
        status, ready = _get(url + "/readyz")
        assert status == 503 and "draining" in ready["reasons"]
        status, health = _get(url + "/healthz")
        assert status == 200 and health["draining"] is True
    finally:
        handle.shutdown()


def test_disarmed_faultpoints_preserve_parity_and_compile_bound(
    stacking_params
):
    """Acceptance: with faults disarmed the hot path is untouched —
    bit-identical predictions through the supervised engine and the same
    one-compile-per-bucket bound."""
    from machine_learning_replications_tpu.serve import (
        BucketedPredictEngine,
    )

    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8))
    sup = SupervisedEngine(eng, lambda: eng)
    rng = np.random.default_rng(3)
    X = rng.normal(size=(11, 17))
    baseline = eng.predict(X)
    # Arm + fully exhaust a schedule, then compare: the registry must
    # leave no residue on the compute path
    faults.arm("engine.compute:raise@n=1")
    with pytest.raises(faults.InjectedFault):
        eng.predict(X[:1])
    np.testing.assert_array_equal(sup.predict(X), baseline)
    # No extra compiles: the injected raise fired BEFORE the compute, so
    # the jit cache never even saw the aborted call's bucket
    assert eng.trace_counts == {8: 1}
    sup.close()


# ---------------------------------------------------------------------------
# graceful drain under adversity
# ---------------------------------------------------------------------------


def test_sigterm_drain_with_inflight_and_client_disconnect(stacking_params):
    """The satellite contract: SIGTERM while requests are in flight, plus
    a client that disconnects mid-drain, completes the drain without
    losing or double-answering any request. (SIGTERM -> shutdown-thread is
    the cli serve handler's exact shape.)"""
    handle = make_server(
        stacking_params, port=0, buckets=(1, 8), max_wait_ms=1.0,
        max_queue=64,
    ).start_background()
    host, port = handle.address
    url = f"http://{host}:{port}"

    # Slow the engine so requests are genuinely in flight at SIGTERM
    real_predict = handle.batcher._engine.predict

    def slow_predict(X):
        time.sleep(0.25)
        return real_predict(X)

    handle.batcher._engine = type("Slow", (), {
        "predict": staticmethod(slow_predict),
        "bucket_for": staticmethod(handle.engine.bucket_for),
    })()

    status, body, _ = _post(url + "/predict", dict(EXAMPLE_PATIENT))
    golden = body["probability"]

    results: list[tuple] = []
    res_lock = threading.Lock()

    def client(i):
        try:
            status, body, _ = _post(
                url + "/predict", dict(EXAMPLE_PATIENT), timeout=30.0
            )
            with res_lock:
                results.append(("ok", body["probability"]))
        except urllib.error.HTTPError as exc:
            exc.read()
            with res_lock:
                results.append((f"http_{exc.code}", None))
        except Exception as exc:
            with res_lock:
                results.append((f"err_{type(exc).__name__}", None))

    shutdown_threads: list[threading.Thread] = []

    def on_sigterm(signum, frame):
        th = threading.Thread(target=handle.shutdown, daemon=True)
        th.start()
        shutdown_threads.append(th)

    old = signal.signal(signal.SIGTERM, on_sigterm)
    try:
        clients = [
            threading.Thread(target=client, args=(i,)) for i in range(6)
        ]
        for t in clients:
            t.start()
        time.sleep(0.1)  # let them reach the (slow) batcher

        # The adversarial client: sends a full request, hangs up before
        # the reply — mid-drain its write will fail server-side.
        raw = socket.create_connection((host, port), timeout=5.0)
        payload = json.dumps(dict(EXAMPLE_PATIENT)).encode()
        raw.sendall(
            b"POST /predict HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(payload)).encode()
            + b"\r\n\r\n" + payload
        )
        time.sleep(0.05)

        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.05)
        raw.close()  # the mid-drain disconnect

        for t in clients:
            t.join(timeout=30.0)
            assert not t.is_alive(), "a client hung through the drain"
        for th in shutdown_threads:
            th.join(timeout=30.0)
            assert not th.is_alive(), "shutdown (drain) never completed"
    finally:
        signal.signal(signal.SIGTERM, old)
        handle.shutdown()  # idempotent

    # Exactly one reply per surviving client; every admitted request
    # either answered correctly or failed explicitly (shed at admission
    # close) — nothing lost, nothing double-answered, nothing wrong.
    assert len(results) == 6
    for kind, prob in results:
        if kind == "ok":
            assert prob == golden
        else:
            assert kind in ("http_503",), f"unexpected outcome {kind}"
    assert sum(1 for k, _ in results if k == "ok") >= 1
