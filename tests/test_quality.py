"""Model-quality observability (obs.quality): drift math on analytic
distributions, streaming monitor semantics, journal transitions, and
exposition validity of the quality_* families.

The golden tests pin the PSI/KS implementations to values computable by
hand: identical distributions must sit at ~0, a shifted normal must match
the analytic PSI derived from normal CDF bin masses over the profile's
own edges, and a shifted uniform must produce its textbook KS distance.
Low-count windows must say ``None`` (strict JSON), never NaN — the PR 1
metrics convention.
"""

import json
import math
import os
import sys

import numpy as np
import pytest

from machine_learning_replications_tpu.obs import journal, quality
from machine_learning_replications_tpu.obs.registry import MetricsRegistry

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))
try:
    import validate_metrics
finally:
    sys.path.pop(0)


def _norm_cdf(x, mu=0.0, sigma=1.0):
    return 0.5 * (1.0 + math.erf((x - mu) / (sigma * math.sqrt(2.0))))


def _profile(X, scores=None, y=None, **kw):
    if scores is None:
        scores = np.full(X.shape[0], 0.5)
    return quality.build_reference_profile(X, scores, y=y, **kw)


# ---------------------------------------------------------------------------
# drift math: golden values on analytic distributions
# ---------------------------------------------------------------------------


def test_psi_identical_distribution_is_near_zero():
    rng = np.random.default_rng(0)
    a = rng.normal(size=100_000)
    b = rng.normal(size=100_000)
    edges = np.linspace(-4, 4, 11)
    ca, _ = np.histogram(np.clip(a, -4, 4), edges)
    cb, _ = np.histogram(np.clip(b, -4, 4), edges)
    assert quality.psi(ca, cb) == pytest.approx(0.0, abs=5e-3)
    assert quality.psi(ca, ca) == 0.0
    assert quality.ks_binned(ca, ca) == 0.0


def test_psi_shifted_normal_matches_analytic_value():
    """PSI of N(0.5, 1) traffic against an N(0, 1) reference, on the
    reference profile's own equal-width edges, must match the value
    computed independently from normal CDF bin masses."""
    rng = np.random.default_rng(1)
    n = 200_000
    ref = rng.normal(size=n)
    shifted = rng.normal(loc=0.5, size=n)
    prof = _profile(ref[:, None])
    edges = prof["bin_edges"][0]
    # Analytic bin masses with the edge bins open (the monitor clips
    # out-of-range values into them), floored at the PSI eps exactly as
    # the implementation floors empirical proportions.
    eps = 1e-4

    def masses(mu):
        cdf = [0.0] + [_norm_cdf(e, mu) for e in edges[1:-1]] + [1.0]
        return np.maximum(np.diff(cdf), eps)

    p_e, p_a = masses(0.0), masses(0.5)
    expected = float(np.sum((p_a - p_e) * np.log(p_a / p_e)))
    mins = prof["bin_edges"][:, 0]
    widths = prof["bin_edges"][:, -1] - mins
    counts = np.bincount(
        quality._feature_bin_indices(
            shifted[:, None], mins, widths, prof["bin_counts"].shape[1]
        )[:, 0],
        minlength=prof["bin_counts"].shape[1],
    )
    got = quality.psi(prof["bin_counts"][0], counts)
    assert got == pytest.approx(expected, rel=0.05)
    assert got > quality.DEFAULT_WARN_PSI  # a half-sigma shift must warn


def test_ks_binned_shifted_uniform_golden():
    """U(0, 1) reference vs U(0.25, 1.25) traffic: the exact KS distance
    is 0.25, and with traffic clipped into the reference's [0, 1] bins
    the binned estimate must land there too."""
    rng = np.random.default_rng(2)
    n = 200_000
    ref = rng.uniform(0, 1, size=n)
    traffic = rng.uniform(0.25, 1.25, size=n)
    edges = np.linspace(0, 1, 11)
    c_ref, _ = np.histogram(ref, edges)
    c_tr, _ = np.histogram(np.clip(traffic, 0, 1), edges)
    assert quality.ks_binned(c_ref, c_tr) == pytest.approx(0.25, abs=0.01)


def test_psi_ks_reject_malformed_histograms():
    with pytest.raises(ValueError, match="shapes"):
        quality.psi([1, 2, 3], [1, 2])
    with pytest.raises(ValueError, match="non-empty"):
        quality.psi([0, 0], [1, 2])
    with pytest.raises(ValueError, match="shapes"):
        quality.ks_binned([1, 2, 3], [1, 2])
    with pytest.raises(ValueError, match="non-empty"):
        quality.ks_binned([1, 2], [0, 0])


# ---------------------------------------------------------------------------
# reference profile
# ---------------------------------------------------------------------------


def test_reference_profile_shapes_and_contents():
    rng = np.random.default_rng(3)
    X = rng.normal(size=(500, 17))
    scores = rng.uniform(0, 1, size=500)
    y = (scores > 0.5).astype(float)
    prof = quality.build_reference_profile(X, scores, y)
    F, B = 17, quality.DEFAULT_FEATURE_BINS
    S = quality.DEFAULT_SCORE_BINS
    assert prof["bin_edges"].shape == (F, B + 1)
    assert prof["bin_counts"].shape == (F, B)
    np.testing.assert_array_equal(prof["bin_counts"].sum(axis=1), 500)
    np.testing.assert_allclose(prof["mean"], X.mean(axis=0))
    assert prof["score_counts"].shape == (S,)
    assert prof["score_counts"].sum() == 500
    # calibration: every populated score bin's training pos rate is the
    # label mean of the scores that landed there
    sidx = np.clip((scores * S).astype(int), 0, S - 1)
    for b in range(S):
        m = sidx == b
        if m.any():
            assert prof["calib_pos_rate"][b] == pytest.approx(y[m].mean())
    # every value is an ndarray — the contract that lets the Orbax sidecar
    # carry the profile as a plain mapping node
    assert all(isinstance(v, np.ndarray) for v in prof.values())


def test_reference_profile_rejects_bad_input():
    with pytest.raises(ValueError, match="finite"):
        quality.build_reference_profile(
            np.array([[1.0, np.nan]]), np.array([0.5])
        )
    with pytest.raises(ValueError, match="scores length"):
        quality.build_reference_profile(
            np.ones((3, 2)), np.array([0.5])
        )
    with pytest.raises(ValueError, match="non-empty"):
        quality.build_reference_profile(
            np.ones((0, 2)), np.zeros(0)
        )


def test_constant_feature_is_degenerate_but_finite():
    X = np.ones((100, 2))
    X[:, 1] = np.linspace(0, 1, 100)
    prof = _profile(X)
    m = quality.QualityMonitor(prof, registry=MetricsRegistry(), min_rows=10,
                               feature_names=("const", "ramp"))
    m.observe_batch(X, np.full(100, 0.5))
    snap = m.snapshot(detail=True)
    by_name = {f["name"]: f for f in snap["features"]}
    assert by_name["const"]["psi"] == pytest.approx(0.0, abs=1e-9)


# ---------------------------------------------------------------------------
# streaming monitor
# ---------------------------------------------------------------------------


def _stable_monitor(n_ref=4000, window=1024, **kw):
    rng = np.random.default_rng(4)
    X = rng.normal(size=(n_ref, 17))
    scores = 1.0 / (1.0 + np.exp(-X @ rng.normal(size=17) / 4.0))
    prof = quality.build_reference_profile(X, scores, (scores > 0.5).astype(float))
    # Zero time floor so back-to-back observes refresh synchronously —
    # these tests exercise the statistics; the production 1 s throttle
    # has its own test below.
    kw.setdefault("refresh_interval_s", 0.0)
    mon = quality.QualityMonitor(
        prof, window=window, registry=MetricsRegistry(), **kw
    )
    return mon, X, scores, rng


def test_low_count_window_is_null_not_nan():
    mon, X, scores, _ = _stable_monitor(min_rows=50)
    mon.observe_batch(X[:10], scores[:10])
    snap = mon.snapshot(detail=True)
    # strict JSON: the whole payload must serialize with allow_nan=False
    json.dumps(snap, allow_nan=False)
    assert snap["status"] == "ok"
    assert snap["score_psi"] is None
    assert snap["worst_psi"] is None
    assert all(f["psi"] is None and f["ks"] is None for f in snap["features"])
    assert snap["window_rows"] == 10
    assert mon.health() == {
        "status": "ok", "worst_feature": None, "worst_psi": None,
    }


def test_stable_traffic_stays_ok_and_shift_alerts_with_journal(tmp_path):
    mon, X, scores, rng = _stable_monitor(min_rows=100)
    jrn = journal.RunJournal(tmp_path / "j.jsonl", command="test")
    journal.set_journal(jrn)
    try:
        # fresh draws from the SAME distributions: status must stay ok
        # (scores resampled from the reference's own empirical scores —
        # the stable-score leg; the feature legs are fresh normal draws)
        X2 = rng.normal(size=(800, 17))
        mon.observe_batch(X2, rng.choice(scores, size=800))
        assert mon.status == "ok"
        snap = mon.snapshot()
        assert snap["status"] == "ok"
        assert snap["score_psi"] < quality.DEFAULT_WARN_PSI
        # a 3-sigma shift on one feature must alert, and the transition
        # must be journaled with the offender named
        X3 = X2.copy()
        X3[:, 16] += 3.0
        mon.observe_batch(X3, rng.choice(scores, size=800))
        assert mon.status == "alert"
    finally:
        journal.set_journal(None)
        jrn.close()
    events = [
        json.loads(line) for line in open(tmp_path / "j.jsonl")
    ]
    trans = [e for e in events if e.get("kind") == "quality_status"]
    assert [
        (e["from_status"], e["to_status"]) for e in trans
    ] == [("ok", "alert")]
    assert trans[0]["worst_feature"] == "Ejection_Fraction"
    assert trans[0]["worst_psi"] > quality.DEFAULT_ALERT_PSI


def test_window_slides_and_recovers():
    """The ring forgets: after a drift burst, enough clean traffic must
    bring the status back to ok (and journal the recovery transition)."""
    mon, X, scores, rng = _stable_monitor(window=512, min_rows=100)
    bad = X[:512].copy()
    bad[:, 0] += 5.0
    mon.observe_batch(bad, rng.choice(scores, size=512))
    assert mon.status == "alert"
    mon.observe_batch(
        rng.normal(size=(512, 17)), rng.choice(scores, size=512)
    )
    assert mon.status == "ok"
    assert mon.snapshot()["window_rows"] == 512


def test_member_disagreement_windowed_mean():
    mon, X, scores, _ = _stable_monitor(min_rows=10)
    n = 100
    p = np.full(n, 0.5)
    # members at p, p+0.1, p+0.2: pairwise |diffs| = .1, .2, .1 → mean 2/15
    members = np.stack([p, p + 0.1, p + 0.2], axis=1)
    mon.observe_batch(X[:n], p, members)
    snap = mon.snapshot()
    # snapshot rounds to 6 decimals for payload compactness
    assert snap["member_disagreement"] == pytest.approx(2.0 / 15.0, abs=1e-6)
    # no members (e.g. a bare GBDT) → null, not NaN
    mon2, X2, s2, _ = _stable_monitor(min_rows=10)
    mon2.observe_batch(X2[:n], p)
    assert mon2.snapshot()["member_disagreement"] is None


def test_oversized_batch_keeps_newest_window_rows():
    mon, X, scores, rng = _stable_monitor(window=256, min_rows=10)
    big = np.concatenate([X[:300], X[:300] + 9.0])  # old clean, new shifted
    mon.observe_batch(big, np.concatenate([scores[:300]] * 2))
    snap = mon.snapshot()
    assert snap["window_rows"] == 256
    assert snap["rows_total"] == 600  # truncation must not shrink the count
    assert snap["status"] == "alert"  # only the (shifted) tail survived


def test_monitor_validates_construction():
    mon, X, scores, _ = _stable_monitor()
    prof = mon._profile
    with pytest.raises(ValueError, match="warn_psi"):
        quality.QualityMonitor(prof, warn_psi=0.5, alert_psi=0.25,
                               registry=MetricsRegistry())
    with pytest.raises(ValueError, match=">= 1"):
        quality.QualityMonitor(prof, window=0, registry=MetricsRegistry())
    with pytest.raises(ValueError, match="never be computed"):
        # a window that can never reach min_rows would silently pin the
        # status at ok forever — refused at construction
        quality.QualityMonitor(prof, window=128, min_rows=200,
                               registry=MetricsRegistry())
    with pytest.raises(ValueError, match="feature names"):
        quality.QualityMonitor(prof, feature_names=("just_one",),
                               registry=MetricsRegistry())
    with pytest.raises(TypeError, match="dict"):
        quality.QualityMonitor(object(), registry=MetricsRegistry())
    with pytest.raises(ValueError, match="missing keys"):
        quality.QualityMonitor({"bin_edges": np.zeros((2, 3))},
                               registry=MetricsRegistry())
    with pytest.raises(ValueError, match="version"):
        bad = dict(prof)
        bad["version"] = np.asarray(quality.PROFILE_VERSION + 1)
        quality.QualityMonitor(bad, registry=MetricsRegistry())
    with pytest.raises(ValueError, match="shapes"):
        mon.observe_batch(np.ones((3, 5)), np.ones(3))
    with pytest.raises(ValueError, match="finite"):
        bad_rows = np.ones((3, 17))
        bad_rows[1, 4] = np.nan
        mon.observe_batch(bad_rows, np.ones(3))


# ---------------------------------------------------------------------------
# exposition
# ---------------------------------------------------------------------------


def test_quality_families_are_exposition_valid_before_and_after_traffic():
    """The quality_* families must render a strict-validator-clean page in
    every monitor state: freshly constructed (drift gauges NaN = no data),
    below min_rows, and after a full refresh."""
    rng = np.random.default_rng(5)
    X = rng.normal(size=(1000, 17))
    scores = rng.uniform(0, 1, 1000)
    reg = MetricsRegistry()
    mon = quality.QualityMonitor(
        _profile(X, scores), registry=reg, min_rows=50
    )
    page = reg.render_prometheus()
    assert validate_metrics.validate(page) == []
    # the JSON snapshot of the same registry must be strict JSON even
    # while the drift gauges hold their NaN "no data" value (they become
    # null — the /metrics?format=json page embeds this snapshot)
    json.dumps(reg.snapshot(), allow_nan=False)
    assert reg.snapshot()["quality_score_psi"] is None
    for name in (
        "quality_feature_psi", "quality_feature_ks", "quality_score_psi",
        "quality_member_disagreement", "quality_window_rows",
        "quality_status", "quality_rows_total",
        "quality_status_transitions_total",
    ):
        assert name in page, f"{name} missing from first scrape"
    mon.observe_batch(X[:10], scores[:10])
    assert validate_metrics.validate(reg.render_prometheus()) == []
    mon.observe_batch(X, scores)
    page = reg.render_prometheus()
    assert validate_metrics.validate(page) == []
    # after refresh the gauges carry real (finite) values
    for line in page.splitlines():
        if line.startswith("quality_score_psi "):
            assert float(line.split()[-1]) < quality.DEFAULT_WARN_PSI


def test_vectorized_refresh_matches_scalar_oracle():
    """The refresh path's row-wise PSI/KS (one flat bincount + 2D math —
    the r12 hot-path rewrite) must agree with the scalar spec functions
    to float precision on every feature."""
    mon, X, scores, rng = _stable_monitor(window=512, min_rows=50)
    mon.observe_batch(rng.normal(size=(512, 17)) * 1.3 + 0.2,
                      rng.choice(scores, size=512))
    snap = mon.snapshot(detail=True)
    ref = mon._profile["bin_counts"]
    for f in range(17):
        counts = np.bincount(mon._feat_ring[:512, f], minlength=10)
        expect_psi = quality.psi(ref[f], counts)
        expect_ks = quality.ks_binned(ref[f], counts)
        got = next(
            d for d in snap["features"]
            if d["name"] == mon.feature_names[f]
        )
        assert got["psi"] == pytest.approx(expect_psi, abs=1e-6)
        assert got["ks"] == pytest.approx(expect_ks, abs=1e-6)


def test_refresh_interval_throttles_observe_but_not_snapshot():
    """The r12 saturated-flush-loop guard: back-to-back observes inside
    the time floor skip the PSI pass (the status lags), but snapshot()
    always forces a fresh computation."""
    mon, X, scores, rng = _stable_monitor(
        window=512, min_rows=100, refresh_interval_s=3600.0
    )
    mon.observe_batch(X[:512], rng.choice(scores, size=512))
    assert mon.status == "ok"  # first refresh fires (never refreshed yet)
    shifted = X[:512].copy()
    shifted[:, 0] += 5.0
    mon.observe_batch(shifted, rng.choice(scores, size=512))
    # inside the floor: observe did NOT recompute...
    assert mon.status == "ok"
    # ...but an explicit snapshot always does (and journals transitions)
    assert mon.snapshot()["status"] == "alert"
    assert mon.status == "alert"


def test_status_gauge_and_transition_counter_track_status():
    rng = np.random.default_rng(6)
    X = rng.normal(size=(1000, 17))
    scores = rng.uniform(0, 1, 1000)
    reg = MetricsRegistry()
    mon = quality.QualityMonitor(
        _profile(X, scores), registry=reg, min_rows=50, window=512
    )
    mon.observe_batch(X[:512] + 7.0, scores[:512])
    snap = reg.snapshot()
    assert snap["quality_status"] == 2.0  # alert
    assert snap["quality_status_transitions_total"]["to=alert"] == 1
    assert snap["quality_rows_total"] == 512
    assert snap["quality_window_rows"] == 512.0
