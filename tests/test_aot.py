"""AOT-serialized engines (persist/aot.py + the engine restore path,
docs/AOT.md): publish-time export, restore-instead-of-trace warmup,
bit-identical AOT-vs-traced outputs, the fingerprint/corruption/parity
fails-open fallbacks, the integrity-manifest round trip, the
``persist.aot_restore`` faultpoint, and the coldstart bench's --tiny
smoke (ISSUE 15 acceptance: the restore path exercised on every CI run).
"""

import json
import os
import shutil
import subprocess
import sys
import warnings

import numpy as np
import pytest

from machine_learning_replications_tpu.obs import journal
from machine_learning_replications_tpu.persist import aot, orbax_io
from machine_learning_replications_tpu.resilience import faults
from machine_learning_replications_tpu.serve.engine import (
    BucketedPredictEngine,
)

BUCKETS = (1, 8)


@pytest.fixture(scope="module")
def params():
    """A small live sklearn-fitted stacking ensemble (the import route,
    available everywhere — same shape as the serve suite's fixture)."""
    from sklearn.ensemble import (
        GradientBoostingClassifier, StackingClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    from machine_learning_replications_tpu.persist import import_stacking

    rng = np.random.default_rng(7)
    X = rng.normal(size=(160, 17))
    y = (X @ rng.normal(size=17) > 0).astype(float)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = StackingClassifier(
            estimators=[
                ("svc", make_pipeline(
                    StandardScaler(), SVC(probability=True, random_state=0),
                )),
                ("gbc", GradientBoostingClassifier(
                    n_estimators=5, max_depth=1, random_state=0)),
                ("lg", LogisticRegression()),
            ],
            final_estimator=LogisticRegression(),
        ).fit(X, y)
    return import_stacking(clf)


@pytest.fixture(scope="module")
def other_params(params):
    """The same model with perturbed meta weights: IDENTICAL shapes (so
    its executables load and run against ``params``), different bits —
    wrong-weights material for the parity-mismatch guard."""
    from machine_learning_replications_tpu.models import linear

    return params.replace(
        meta=linear.LinearParams(
            coef=np.asarray(params.meta.coef) * 1.5 + 0.25,
            intercept=np.asarray(params.meta.intercept) - 0.5,
        )
    )


@pytest.fixture(scope="module")
def ckpt(params, tmp_path_factory):
    """One published checkpoint WITH its AOT bundle, restored once — the
    (restored params, bundle, path) triple most tests consume."""
    path = str(tmp_path_factory.mktemp("aot") / "model")
    orbax_io.save_model(path, params, aot=True)
    restored = orbax_io.load_model(path)
    return restored, aot.load_bundle(path), path


@pytest.fixture()
def captured_journal(tmp_path):
    path = tmp_path / "journal.jsonl"
    jrn = journal.RunJournal(path, command="test")
    journal.set_journal(jrn)
    try:
        yield path
    finally:
        journal.set_journal(None)
        jrn.close()


def _events(path) -> list[dict]:
    with open(path) as f:
        return [json.loads(line) for line in f]


def _query_rows(n: int = 70) -> np.ndarray:
    base = np.asarray(
        [[63, 1, 1, 1, 0, 0, 0, 1, 0, 1, 0, 145, 1, 20, 1.2, 38, 140]],
        np.float64,
    )
    return np.repeat(base, n, axis=0) * (
        1.0 + 0.001 * np.arange(n)[:, None]
    )


# -- export / publish --------------------------------------------------------


def test_publish_with_aot_integrity_roundtrip(ckpt):
    """The AOT blobs are ordinary checkpoint payload: covered by
    integrity.json, deep-verified, and the aot manifest indexes exactly
    the blob files on disk."""
    _params, bundle, path = ckpt
    assert orbax_io.verify_checkpoint(path, deep=True)
    integrity = json.load(open(os.path.join(path, "integrity.json")))
    aot_files = sorted(
        k for k in integrity["files"] if k.startswith("aot/")
    )
    assert "aot/manifest.json" in aot_files
    blobs = bundle.manifest["blobs"]
    assert sorted(f"aot/{b['file']}" for b in blobs) == sorted(
        f for f in aot_files if f.endswith(".bin")
    )
    # The default export covers the device ladder ∪ host ladder on CPU.
    from machine_learning_replications_tpu.serve.engine import (
        DEFAULT_BUCKETS,
    )

    assert {b["bucket"] for b in blobs} == set(DEFAULT_BUCKETS)
    assert all(b["backend"] == "cpu" for b in blobs)
    assert bundle.family == "stacking"  # the family_core kind


def test_corrupting_a_blob_fails_deep_verification(params, tmp_path):
    """Post-publish blob rot is caught where all checkpoint rot is:
    integrity verification, BEFORE anything deserializes it."""
    path = str(tmp_path / "model")
    orbax_io.save_model(path, params, aot=True)
    blob = next(
        os.path.join(path, "aot", f)
        for f in sorted(os.listdir(os.path.join(path, "aot")))
        if f.endswith(".bin")
    )
    with open(blob, "r+b") as f:
        first = f.read(1)
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]))
    with pytest.raises(orbax_io.CheckpointIntegrityError):
        orbax_io.verify_checkpoint(path, deep=True)


# -- restore: the happy path -------------------------------------------------


def test_aot_restore_bit_identical_and_compile_free(ckpt, captured_journal):
    """The tentpole contract: an AOT-restored engine compiles NOTHING at
    warmup and serves bit-identical probabilities to a traced engine —
    per bucket, across split plans, on the same restored params."""
    params, bundle, _path = ckpt
    traced = BucketedPredictEngine(params, buckets=BUCKETS)
    traced.warmup()
    restored = BucketedPredictEngine(
        params, buckets=BUCKETS, aot=bundle.for_backend("cpu")
    )
    restored.warmup()
    assert restored.compile_count() == 0, restored.trace_counts
    assert traced.compile_count() == len(BUCKETS)
    assert sorted(restored._aot_execs) == sorted(BUCKETS)
    rows = _query_rows(70)  # plans across 1/8 incl. padding + splits
    for n in (1, 3, 8, 70):
        a = traced.predict(rows[:n])
        b = restored.predict(rows[:n])
        assert (a == b).all(), f"bit mismatch at n={n}"
    kinds = [e["kind"] for e in _events(captured_journal)]
    assert kinds.count("aot_restore") == len(BUCKETS)
    assert "aot_fallback" not in kinds


def test_host_scorer_restores_from_cpu_view(ckpt):
    from machine_learning_replications_tpu.serve.hostpath import HostScorer

    params, bundle, _path = ckpt
    scorer = HostScorer(
        params, buckets=(1, 8), aot=bundle.for_backend("cpu")
    )
    scorer.warmup()
    assert scorer._engine.compile_count() == 0
    traced = HostScorer(params, buckets=(1, 8))
    traced.warmup()
    row = _query_rows(1)
    assert float(scorer.predict(row)[0]) == float(traced.predict(row)[0])


def test_missing_bucket_falls_back_to_trace_for_that_bucket_only(
    ckpt, captured_journal
):
    """A ladder bucket the bundle never exported (here: 13 is not a
    default-ladder bucket) traces; the covered buckets still restore."""
    params, bundle, _path = ckpt
    eng = BucketedPredictEngine(
        params, buckets=(1, 8, 13), aot=bundle.for_backend("cpu")
    )
    eng.warmup()
    assert sorted(eng._aot_execs) == [1, 8]
    assert eng.compile_count() == 1  # bucket 13 traced
    events = _events(captured_journal)
    fb = [e for e in events if e["kind"] == "aot_fallback"]
    assert [e.get("bucket") for e in fb] == [13]
    assert fb[0]["reason"] == "missing_bucket"


# -- restore: the fails-open ladder ------------------------------------------


def test_fingerprint_mismatch_falls_back_to_tracing(
    params, tmp_path, captured_journal
):
    path = str(tmp_path / "model")
    orbax_io.save_model(path, params, aot=True)
    man_path = os.path.join(path, "aot", "manifest.json")
    man = json.load(open(man_path))
    man["fingerprints"]["cpu"]["jax"] = "0.0.0-not-this-jax"
    with open(man_path, "w") as f:
        json.dump(man, f)
    bundle = aot.load_bundle(path)
    eng = BucketedPredictEngine(
        params, buckets=BUCKETS, aot=bundle.for_backend("cpu")
    )
    eng.warmup()
    assert not eng._aot_execs
    assert eng.compile_count() == len(BUCKETS)  # traced everything
    fb = [
        e for e in _events(captured_journal) if e["kind"] == "aot_fallback"
    ]
    assert len(fb) == 1 and fb[0]["reason"] == "fingerprint_mismatch"
    assert "jax" in fb[0]["detail"]
    # ... and the engine still serves (correctness never depended on AOT).
    assert eng.predict(_query_rows(3)).shape == (3,)


def test_wrong_family_bundle_rejected(ckpt, captured_journal):
    params, bundle, _path = ckpt
    view = bundle.for_backend("cpu")
    bad = view.unusable_reason("pipeline")
    assert bad is not None and bad[0] == "family_mismatch"
    assert view.unusable_reason("stacking") is None
    assert view.unusable_reason(None) is None
    # A backend the bundle never exported reads as missing_backend —
    # NOT fingerprint skew (an operator alert on fingerprint_mismatch
    # means "rebuild artifacts"; this one means "expected on this host").
    bad = bundle.for_backend("tpu").unusable_reason("stacking")
    assert bad is not None and bad[0] == "missing_backend"


def test_corrupt_blob_deserialize_falls_back(
    params, tmp_path, captured_journal
):
    """A blob whose bytes are bad AT PUBLISH (torn, then re-manifested so
    the checkpoint itself verifies): deserialization fails, the bucket
    journals a fallback and traces, predictions stay correct."""
    path = str(tmp_path / "model")
    orbax_io.save_model(path, params, aot=True)
    for name in os.listdir(os.path.join(path, "aot")):
        if name.endswith(".bin"):
            with open(os.path.join(path, "aot", name), "r+b") as f:
                first = f.read(1)
                f.seek(0)
                f.write(bytes([first[0] ^ 0xFF]))
    orbax_io._write_integrity(path, version=orbax_io.checkpoint_version(path))
    assert orbax_io.verify_checkpoint(path, deep=True)  # "intact" ckpt
    bundle = aot.load_bundle(path)
    eng = BucketedPredictEngine(
        params, buckets=BUCKETS, aot=bundle.for_backend("cpu")
    )
    eng.warmup()
    assert not eng._aot_execs
    assert eng.compile_count() == len(BUCKETS)
    fb = [
        e for e in _events(captured_journal) if e["kind"] == "aot_fallback"
    ]
    assert len(fb) == len(BUCKETS)
    assert {e["reason"] for e in fb} == {"deserialize_error"}
    traced = BucketedPredictEngine(params, buckets=BUCKETS)
    traced.warmup()
    rows = _query_rows(5)
    assert (eng.predict(rows) == traced.predict(rows)).all()


def test_foreign_same_shape_bundle_serves_live_params_bits(
    params, other_params, tmp_path, captured_journal
):
    """Params ride the executables as runtime ARGUMENTS, so a blob is
    weight-agnostic: a bundle exported from a same-shaped checkpoint
    with different weights restores cleanly and computes with the LIVE
    engine's params — bit-identical to tracing them. (Structural
    mismatches — different support-vector counts, different families —
    fail the load or the probe instead; see the fallback tests.)"""
    path_a = str(tmp_path / "model_a")
    path_b = str(tmp_path / "model_b")
    orbax_io.save_model(path_a, other_params, aot=True)
    orbax_io.save_model(path_b, params)
    shutil.copytree(
        os.path.join(path_a, "aot"), os.path.join(path_b, "aot")
    )
    bundle = aot.load_bundle(path_b)
    eng = BucketedPredictEngine(
        params, buckets=BUCKETS, aot=bundle.for_backend("cpu")
    )
    eng.warmup()
    assert sorted(eng._aot_execs) == sorted(BUCKETS)
    assert eng.compile_count() == 0
    traced = BucketedPredictEngine(params, buckets=BUCKETS)
    traced.warmup()
    rows = _query_rows(5)
    assert (eng.predict(rows) == traced.predict(rows)).all()


def test_parity_mismatch_discards_restored_executable(
    params, captured_journal
):
    """The warmup parity probe: a restored executable that cannot
    reproduce the eager oracle (a miscompile, a garbage blob that
    nonetheless deserialized and ran) is discarded per bucket, the
    bucket re-traces, and the engine serves the oracle's bits."""

    class _WrongBitsView:
        backend = "cpu"

        def unusable_reason(self, family=None):
            return None

        def load_exec(self, bucket, in_tree, out_tree):
            def fn(arg, X):
                n = int(X.shape[0])
                return np.full((n,), 0.123), np.zeros((n, 3))

            return fn

    eng = BucketedPredictEngine(
        params, buckets=BUCKETS, aot=_WrongBitsView()
    )
    eng.warmup()
    assert not eng._aot_execs  # every bucket failed the probe
    assert eng.compile_count() == len(BUCKETS)  # all re-traced
    assert eng.warm
    fb = [
        e for e in _events(captured_journal) if e["kind"] == "aot_fallback"
    ]
    assert {e["reason"] for e in fb} == {"parity_mismatch"}
    assert sorted(e["bucket"] for e in fb) == sorted(BUCKETS)
    traced = BucketedPredictEngine(params, buckets=BUCKETS)
    traced.warmup()
    rows = _query_rows(5)
    assert (eng.predict(rows) == traced.predict(rows)).all()


def test_aot_restore_faultpoint_raise_and_corrupt(ckpt, captured_journal):
    """The ``persist.aot_restore`` faultpoint (docs/RESILIENCE.md): raise
    = a failing restore, corrupt = torn blob bytes in flight — both
    resolve to the journaled tracing fallback, never an unready engine."""
    params, bundle, _path = ckpt
    try:
        faults.arm("persist.aot_restore:raise@n=1")
        eng = BucketedPredictEngine(
            params, buckets=BUCKETS, aot=bundle.for_backend("cpu")
        )
        eng.warmup()
        # First bucket's load raised; the second restored.
        assert sorted(eng._aot_execs) == [8]
        assert eng.compile_count() == 1
        faults.arm("persist.aot_restore:corrupt")
        eng2 = BucketedPredictEngine(
            params, buckets=BUCKETS, aot=bundle.for_backend("cpu")
        )
        eng2.warmup()
        assert not eng2._aot_execs
        assert eng2.warm
    finally:
        faults.reset()
    kinds = [e["kind"] for e in _events(captured_journal)]
    assert "fault_injected" in kinds and "aot_fallback" in kinds


# -- serving stack wiring ----------------------------------------------------


def test_make_server_serves_identical_bits_with_and_without_aot(ckpt):
    """make_server(aot_bundle=…) answers /predict with the same bytes a
    --no-aot stack produces, and its warmup/restore gauges render a
    strict-valid exposition."""
    import urllib.request

    from machine_learning_replications_tpu.data.examples import (
        EXAMPLE_PATIENT,
    )
    from machine_learning_replications_tpu.serve import make_server

    params, bundle, _path = ckpt

    def one_probability(**kw):
        handle = make_server(
            params, port=0, buckets=BUCKETS, max_wait_ms=1.0,
            host_path=True, host_buckets=BUCKETS, **kw
        ).start_background()
        try:
            host, port = handle.address
            req = urllib.request.Request(
                f"http://{host}:{port}/predict",
                data=json.dumps(dict(EXAMPLE_PATIENT)).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as r:
                return json.loads(r.read())["probability"]
        finally:
            handle.shutdown()

    p_aot = one_probability(aot_bundle=bundle)
    p_traced = one_probability(aot_bundle=bundle, use_aot=False)
    assert p_aot == p_traced

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "tools")
    )
    try:
        from validate_metrics import validate
    finally:
        sys.path.pop(0)

    from machine_learning_replications_tpu.obs.registry import REGISTRY

    page = REGISTRY.render_prometheus()
    assert "serve_warmup_seconds" in page
    assert "serve_aot_restore_seconds" in page
    assert "serve_aot_fallback_total" in page
    assert not validate(page)


def test_missing_bundle_is_silently_absent(params, tmp_path):
    path = str(tmp_path / "model")
    orbax_io.save_model(path, params)  # no aot
    assert aot.load_bundle(path) is None
    assert not os.path.exists(os.path.join(path, "aot"))


def test_unreadable_manifest_fails_open(params, tmp_path, captured_journal):
    path = str(tmp_path / "model")
    orbax_io.save_model(path, params, aot=True)
    with open(os.path.join(path, "aot", "manifest.json"), "w") as f:
        f.write("{not json")
    assert aot.load_bundle(path) is None
    fb = [
        e for e in _events(captured_journal) if e["kind"] == "aot_fallback"
    ]
    assert len(fb) == 1 and fb[0]["reason"] == "manifest_unreadable"


def test_fleet_replica_spec_no_aot_passthrough():
    from machine_learning_replications_tpu.fleet.lifecycle import (
        ReplicaSpec,
    )

    spec = ReplicaSpec(model="/m", register_url="http://r", no_aot=True)
    assert "--no-aot" in spec.command("r1", 9000)
    spec = ReplicaSpec(model="/m", register_url="http://r")
    assert "--no-aot" not in spec.command("r1", 9000)


def test_cold_start_rollback_serves_lastgood_version_and_bundle(
    params, other_params, tmp_path
):
    """A replica cold-started on a corrupt primary rolls back to the
    retained last-known-good — and must take its VERSION and its AOT
    bundle from the directory that actually restored, never the corrupt
    target's (the deploy path's info["path"] invariant, now shared by
    `cli serve`): v1 bits labeled v1, restored from v1's blobs."""
    import urllib.request

    from machine_learning_replications_tpu.serve.engine import (
        oracle_proba1,
    )

    path = str(tmp_path / "model")
    orbax_io.save_model(path, params, aot=True)        # v1
    orbax_io.save_model(path, other_params, aot=True)  # v2; v1 → lastgood
    # Tear the primary's largest payload file: integrity verification
    # fails the v2 restore and load_model_versioned serves the v1
    # lastgood (rolled_back).
    best, size = None, -1
    for root, _dirs, names in os.walk(path):
        for name in names:
            fp = os.path.join(root, name)
            if name != "integrity.json" and os.path.getsize(fp) > size:
                best, size = fp, os.path.getsize(fp)
    with open(best, "r+b") as f:
        first = f.read(1)
        f.seek(0)
        f.write(bytes([first[0] ^ 0xFF]) if first else b"\x00")

    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    jpath = str(tmp_path / "serve.jsonl")
    # The test process exports under x64 (conftest); the replica must run
    # the SAME dtype regime or the fingerprint gate — correctly — rejects
    # the bundle as platform skew (x64 decides every compiled aval).
    env = dict(os.environ, JAX_PLATFORMS="cpu", JAX_ENABLE_X64="1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "machine_learning_replications_tpu",
         "serve", "--model", path, "--port", str(port),
         "--buckets", "1,8", "--journal", jpath],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL, env=env,
    )
    try:
        base = f"http://127.0.0.1:{port}"
        deadline = 240
        import time as _time

        t0 = _time.monotonic()
        while True:
            try:
                with urllib.request.urlopen(base + "/healthz", timeout=2) as r:
                    health = json.loads(r.read())
                if health["warm"]:
                    break
            except Exception:
                pass
            assert _time.monotonic() - t0 < deadline, "never warmed"
            _time.sleep(0.2)
        # v1's version, v1's bits, restored (not traced, not fallback'd).
        assert health["model_version"] == 1, health
        from machine_learning_replications_tpu.data.examples import (
            EXAMPLE_PATIENT, patient_row,
        )

        req = urllib.request.Request(
            base + "/predict",
            data=json.dumps(dict(EXAMPLE_PATIENT)).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=30) as r:
            prob = json.loads(r.read())["probability"]
        v1_golden = float(oracle_proba1(params, patient_row())[0])
        v2_golden = float(oracle_proba1(other_params, patient_row())[0])
        # v1's bits at the engine parity tolerance, and decisively NOT
        # the corrupt target's model.
        assert abs(prob - v1_golden) <= 1e-6, (prob, v1_golden)
        assert abs(prob - v2_golden) > 1e-3, (prob, v2_golden)
    finally:
        proc.terminate()
        try:
            proc.wait(timeout=30)
        except subprocess.TimeoutExpired:
            proc.kill()
    kinds = {e["kind"] for e in _events(jpath)}
    assert "checkpoint_rollback" in kinds
    assert "aot_restore" in kinds and "aot_fallback" not in kinds


# -- the CI smoke of the whole arc -------------------------------------------


def test_coldstart_bench_tiny_smoke(tmp_path):
    """The satellite's CI gate: the publish → cold-start → AOT-restore →
    parity → deploy-hold arc end to end over real ``cli serve``
    subprocesses (--tiny: 1,8 ladder, one repeat — seconds, not a
    bench). The tool itself exits non-zero if any contract — restored
    with zero fallbacks, outputs bit-identical — fails."""
    out = tmp_path / "coldstart_tiny.json"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    proc = subprocess.run(
        [sys.executable,
         os.path.join(os.path.dirname(__file__), "..", "tools",
                      "coldstart_bench.py"),
         "--tiny", "--out", str(out)],
        capture_output=True, text=True, timeout=540, env=env,
    )
    assert proc.returncode == 0, (proc.stdout[-2000:], proc.stderr[-2000:])
    artifact = json.loads(out.read_text())
    assert artifact["kind"] == "coldstart_bench"
    assert all(artifact["contracts"].values()), artifact["contracts"]
    assert artifact["cold_start"]["aot"]["ready_s"]
    assert artifact["deploy_hold"]["aot"]["hold_s"]
