"""graftcheck: the invariant checker checked (docs/ANALYSIS.md).

Three layers:

  * an accept/reject fixture matrix per rule — a tiny synthetic package
    per case exercising the clean shape and the violating shape through
    the same ``analysis.core`` API the CLI uses;
  * suppression-comment and baseline-expiry semantics;
  * the repo gate: the checker over THIS repository exits 0 in strict
    mode, so pytest and the CI ``static-analysis`` job enforce the same
    thing;
  * regression tests for the behavioral violations the first run found
    (wall-clock stage durations, lazily-registered metric families,
    jax reachable from the declared-jax-free ``score.reader``).
"""

import datetime
import json
import os
import subprocess
import sys
import textwrap

import pytest

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO_ROOT not in sys.path:
    sys.path.insert(0, REPO_ROOT)

from analysis.core import Baseline, BaselineError, Project, run_rules  # noqa: E402
from analysis.rules import (  # noqa: E402
    ALL_RULES,
    faultpoints,
    import_purity,
    journal_catalog,
    loop_discipline,
    metrics_catalog,
    monotonic_clock,
)


def make_tree(root, files):
    """Write ``{relpath: source}`` under root, creating directories."""
    for rel, src in files.items():
        path = os.path.join(root, rel)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        with open(path, "w") as f:
            f.write(textwrap.dedent(src))


def project_for(root, **kw):
    kw.setdefault("package", "pkg")
    kw.setdefault("tool_dirs", ("tools",))
    return Project(root=str(root), **kw)


def run_one(rule, project, **kw):
    return run_rules(project, [rule], **kw)


# ---------------------------------------------------------------------------
# R1 import-purity
# ---------------------------------------------------------------------------


class TestImportPurity:
    def _project(self, tmp_path, files, jaxfree=("pkg.clean",)):
        make_tree(tmp_path, {"pkg/__init__.py": "", **files})
        return project_for(tmp_path, jaxfree=jaxfree)

    def test_accepts_clean_module(self, tmp_path):
        p = self._project(tmp_path, {
            "pkg/clean.py": "import os\nimport json\n",
        })
        assert run_one(import_purity, p).findings == []

    def test_accepts_lazy_function_scoped_jax(self, tmp_path):
        p = self._project(tmp_path, {
            "pkg/clean.py": "def f():\n    import jax\n    return jax\n",
        })
        assert run_one(import_purity, p).findings == []

    def test_rejects_direct_import(self, tmp_path):
        p = self._project(tmp_path, {"pkg/clean.py": "import jax\n"})
        (f,) = run_one(import_purity, p).findings
        assert f.rule == "import-purity"
        assert "pkg.clean" in f.message and "jax" in f.message

    def test_rejects_transitive_import(self, tmp_path):
        p = self._project(tmp_path, {
            "pkg/clean.py": "from pkg import helper\n",
            "pkg/helper.py": "import jaxlib\n",
        })
        (f,) = run_one(import_purity, p).findings
        assert "pkg.helper" in f.message

    def test_rejects_parent_package_init_edge(self, tmp_path):
        # importing pkg.sub.leaf executes pkg/sub/__init__.py — the
        # score.reader regression this PR fixed
        p = self._project(tmp_path, {
            "pkg/sub/__init__.py": "from pkg.sub.heavy import X\n",
            "pkg/sub/heavy.py": "import jax\nX = 1\n",
            "pkg/sub/leaf.py": "import os\n",
        }, jaxfree=("pkg.sub.leaf",))
        (f,) = run_one(import_purity, p).findings
        assert "pkg.sub.leaf" in f.message and "pkg.sub.heavy" in f.message

    def test_rejects_guarded_module_level_import(self, tmp_path):
        p = self._project(tmp_path, {
            "pkg/clean.py": "try:\n    import jax\nexcept ImportError:"
                            "\n    jax = None\n",
        })
        assert len(run_one(import_purity, p).findings) == 1

    def test_rejects_missing_manifest_module(self, tmp_path):
        p = self._project(tmp_path, {"pkg/clean.py": "import os\n"},
                          jaxfree=("pkg.ghost",))
        (f,) = run_one(import_purity, p).findings
        assert "no such module" in f.message


# ---------------------------------------------------------------------------
# R2 loop-discipline
# ---------------------------------------------------------------------------


_LOOP_HEADER = """\
    from pkg.contracts import loop_only, cross_thread
    import time
"""


class TestLoopDiscipline:
    def _project(self, tmp_path, body):
        make_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/contracts.py": (
                "def loop_only(fn):\n    return fn\n"
                "def cross_thread(fn):\n    return fn\n"
            ),
            "pkg/loopy.py": _LOOP_HEADER + body,
        })
        return project_for(tmp_path)

    def test_accepts_clean_loop_method(self, tmp_path):
        p = self._project(tmp_path, """\
    class S:
        @loop_only
        def tick(self):
            self.n = 1

        @cross_thread
        def post(self, fn):
            self.pending.append(fn)
    """)
        assert run_one(loop_discipline, p).findings == []

    def test_rejects_sleep_in_loop(self, tmp_path):
        p = self._project(tmp_path, """\
    class S:
        @loop_only
        def tick(self):
            time.sleep(1)
    """)
        (f,) = run_one(loop_discipline, p).findings
        assert "time.sleep" in f.message

    def test_rejects_http_client_and_blocking_connect(self, tmp_path):
        p = self._project(tmp_path, """\
    import http.client
    import socket

    class S:
        @loop_only
        def dial(self, addr):
            http.client.HTTPConnection(addr)
            s = socket.socket()
            s.connect(addr)
    """)
        msgs = [f.message for f in run_one(loop_discipline, p).findings]
        assert any("http.client" in m for m in msgs)
        assert any("connect" in m for m in msgs)

    def test_rejects_untimed_acquire_accepts_timed(self, tmp_path):
        p = self._project(tmp_path, """\
    class S:
        @loop_only
        def bad(self):
            self.lock.acquire()

        @loop_only
        def good(self):
            self.lock.acquire(timeout=1.0)
    """)
        findings = run_one(loop_discipline, p).findings
        assert len(findings) == 1 and "acquire" in findings[0].message

    def test_rejects_blocking_true_acquire_variants(self, tmp_path):
        # acquire(True) / acquire(blocking=True) are exactly the
        # un-timed blocking acquire the rule bans; acquire(False),
        # acquire(blocking=False), and acquire(True, 5) are bounded
        p = self._project(tmp_path, """\
    class S:
        @loop_only
        def bad_positional(self):
            self.lock.acquire(True)

        @loop_only
        def bad_keyword(self):
            self.lock.acquire(blocking=True)

        @loop_only
        def ok_nonblocking(self):
            self.lock.acquire(False)
            self.lock.acquire(blocking=False)
            self.lock.acquire(True, 5)
    """)
        findings = run_one(loop_discipline, p).findings
        assert len(findings) == 2
        assert all("acquire" in f.message for f in findings)
        assert {f.message.split(" ")[1].rstrip(":") for f in findings} \
            == {"bad_positional", "bad_keyword"}

    def test_rejects_cross_thread_calling_loop_only(self, tmp_path):
        p = self._project(tmp_path, """\
    class S:
        @loop_only
        def advance(self):
            pass

        @cross_thread
        def send(self):
            self.advance()
    """)
        (f,) = run_one(loop_discipline, p).findings
        assert "advance" in f.message

    def test_accepts_closure_marshalled_call(self, tmp_path):
        # a lambda/closure runs later ON the loop; its body is not the
        # cross-thread function's own thread context
        p = self._project(tmp_path, """\
    class S:
        @loop_only
        def advance(self):
            pass

        @cross_thread
        def send(self):
            self.post(lambda: self.advance())
    """)
        assert run_one(loop_discipline, p).findings == []

    def test_rejects_both_decorators(self, tmp_path):
        p = self._project(tmp_path, """\
    class S:
        @loop_only
        @cross_thread
        def confused(self):
            pass
    """)
        (f,) = run_one(loop_discipline, p).findings
        assert "one thread contract" in f.message


# ---------------------------------------------------------------------------
# R3 metrics-catalog
# ---------------------------------------------------------------------------


_CATALOG = """\
    METRICS = {
        "app_requests_total": ("counter", ("route",)),
        "app_depth": ("gauge", ()),
    }
    EVENTS = {}
"""


class TestMetricsCatalog:
    def _project(self, tmp_path, metrics_src, catalog=_CATALOG,
                 doc="`app_requests_total` `app_depth`"):
        make_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/catalog.py": catalog,
            "pkg/m.py": metrics_src,
            "docs/OBS.md": doc,
        })
        return project_for(
            tmp_path, catalog_path="pkg/catalog.py",
            observability_doc="docs/OBS.md",
        )

    def test_accepts_cataloged_top_level_family(self, tmp_path):
        p = self._project(tmp_path, """\
    REQS = REGISTRY.counter("app_requests_total", "h", labels=("route",))
    DEPTH = REGISTRY.gauge("app_depth", "h")
    """)
        assert run_one(metrics_catalog, p).findings == []

    def test_rejects_nested_global_registration(self, tmp_path):
        p = self._project(tmp_path, """\
    DEPTH = REGISTRY.gauge("app_depth", "h")
    def make():
        return REGISTRY.counter(
            "app_requests_total", "h", labels=("route",))
    """)
        msgs = [f.message for f in run_one(metrics_catalog, p).findings]
        assert any("module import" in m for m in msgs)

    def test_accepts_instance_registry_in_constructor(self, tmp_path):
        p = self._project(tmp_path, """\
    DEPTH = REGISTRY.gauge("app_depth", "h")
    class T:
        def __init__(self, reg):
            self.c = reg.counter(
                "app_requests_total", "h", labels=("route",))
    """)
        assert run_one(metrics_catalog, p).findings == []

    def test_rejects_computed_name(self, tmp_path):
        p = self._project(tmp_path, """\
    REQS = REGISTRY.counter("app_requests_total", "h", labels=("route",))
    DEPTH = REGISTRY.gauge("app_depth", "h")
    EXTRA = REGISTRY.gauge(f"app_{kind}", "h")
    """)
        msgs = [f.message for f in run_one(metrics_catalog, p).findings]
        assert any("string literal" in m for m in msgs)

    def test_rejects_uncataloged_and_naming_violations(self, tmp_path):
        p = self._project(tmp_path, """\
    REQS = REGISTRY.counter("app_requests_total", "h", labels=("route",))
    DEPTH = REGISTRY.gauge("app_depth", "h")
    ROGUE = REGISTRY.counter("app_rogue_count", "h")
    """)
        msgs = [f.message for f in run_one(metrics_catalog, p).findings]
        assert any("not declared in the METRICS catalog" in m
                   for m in msgs)
        assert any("_total" in m for m in msgs)

    def test_rejects_conflicting_label_sets(self, tmp_path):
        p = self._project(tmp_path, """\
    A = REGISTRY.counter("app_requests_total", "h", labels=("route",))
    DEPTH = REGISTRY.gauge("app_depth", "h")
    def other(reg):
        return reg.counter("app_requests_total", "h", labels=("verb",))
    """)
        msgs = [f.message for f in run_one(metrics_catalog, p).findings]
        assert any("conflicting label sets" in m for m in msgs)

    def test_rejects_dead_catalog_entry_and_undocumented(self, tmp_path):
        p = self._project(tmp_path, """\
    DEPTH = REGISTRY.gauge("app_depth", "h")
    """, doc="only `app_depth` documented here")
        msgs = [f.message for f in run_one(metrics_catalog, p).findings]
        assert any("registered nowhere" in m for m in msgs)
        assert any("undocumented" in m for m in msgs)


# ---------------------------------------------------------------------------
# R4 journal-catalog
# ---------------------------------------------------------------------------


_EVENTS_CATALOG = """\
    METRICS = {}
    EVENTS = {
        "stage_done": ("stage", "seconds"),
        "flush": ("seq",),
    }
"""


class TestJournalCatalog:
    def _project(self, tmp_path, src, catalog=_EVENTS_CATALOG):
        make_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/catalog.py": catalog,
            "pkg/j.py": src,
        })
        return project_for(tmp_path, catalog_path="pkg/catalog.py")

    def test_accepts_cataloged_events_with_keys(self, tmp_path):
        p = self._project(tmp_path, """\
    journal.event("stage_done", stage="fit", seconds=1.2)
    journal.event("flush", seq=3, rows=8)
    """)
        assert run_one(journal_catalog, p).findings == []

    def test_rejects_unknown_event_name(self, tmp_path):
        p = self._project(tmp_path, """\
    journal.event("stage_done", stage="fit", seconds=1.2)
    journal.event("flush", seq=1)
    journal.event("stage_doen", stage="fit", seconds=1.2)
    """)
        msgs = [f.message for f in run_one(journal_catalog, p).findings]
        assert any("'stage_doen' is not in the EVENTS catalog" in m
                   for m in msgs)

    def test_rejects_missing_required_key(self, tmp_path):
        p = self._project(tmp_path, """\
    journal.event("stage_done", stage="fit")
    journal.event("flush", seq=1)
    """)
        msgs = [f.message for f in run_one(journal_catalog, p).findings]
        assert any("missing required keys ['seconds']" in m for m in msgs)

    def test_spread_satisfies_keys_but_name_still_checked(self, tmp_path):
        p = self._project(tmp_path, """\
    journal.event("stage_done", **info)
    journal.event("flush", seq=1)
    journal.event("mystery", **info)
    """)
        msgs = [f.message for f in run_one(journal_catalog, p).findings]
        assert len(msgs) == 1 and "mystery" in msgs[0]

    def test_rejects_computed_kind_and_dead_entry(self, tmp_path):
        p = self._project(tmp_path, """\
    journal.event(kind_var, x=1)
    journal.event("stage_done", stage="s", seconds=0.1)
    """)
        msgs = [f.message for f in run_one(journal_catalog, p).findings]
        assert any("string literal" in m for m in msgs)
        assert any("'flush' is emitted nowhere" in m for m in msgs)


# ---------------------------------------------------------------------------
# R5 monotonic-clock
# ---------------------------------------------------------------------------


class TestMonotonicClock:
    def _project(self, tmp_path, src):
        make_tree(tmp_path, {"pkg/__init__.py": "", "pkg/t.py": src})
        return project_for(tmp_path)

    def test_accepts_monotonic_and_perf_counter(self, tmp_path):
        p = self._project(tmp_path, """\
    import time
    t0 = time.perf_counter()
    deadline = time.monotonic() + 5
    """)
        assert run_one(monotonic_clock, p).findings == []

    def test_rejects_wall_clock_calls(self, tmp_path):
        p = self._project(tmp_path, """\
    import time
    import datetime
    a = time.time()
    b = datetime.datetime.now()
    c = datetime.datetime.utcnow()
    """)
        assert len(run_one(monotonic_clock, p).findings) == 3

    def test_line_suppression_allows_timestamps(self, tmp_path):
        p = self._project(tmp_path, """\
    import time
    stamp = time.time()  # graftcheck: disable=monotonic-clock
    dur = time.time()
    """)
        report = run_one(monotonic_clock, p)
        assert len(report.findings) == 1
        assert report.findings[0].line == 3
        assert report.suppressed_count == 1

    def test_file_suppression(self, tmp_path):
        p = self._project(tmp_path, """\
    # graftcheck: disable-file=monotonic-clock
    import time
    a = time.time()
    b = time.time()
    """)
        report = run_one(monotonic_clock, p)
        assert report.findings == [] and report.suppressed_count == 2


# ---------------------------------------------------------------------------
# R6 faultpoint-coherence
# ---------------------------------------------------------------------------


_FAULTS = """\
    SITES = {
        "server.parse": ("raise", "delay"),
        "engine.compute": ("raise", "delay"),
    }
"""
_DOC_OK = """\
    | site | fires where | modes |
    |---|---|---|
    | `server.parse` | admission | raise, delay |
    | `engine.compute` | predict | raise, delay |
"""


class TestFaultpointCoherence:
    def _project(self, tmp_path, fire_src, faults=_FAULTS, doc=_DOC_OK):
        make_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/faults.py": faults,
            "pkg/hot.py": fire_src,
            "docs/RES.md": doc,
        })
        return project_for(
            tmp_path, faults_path="pkg/faults.py",
            resilience_doc="docs/RES.md",
        )

    def test_accepts_coherent_views(self, tmp_path):
        p = self._project(tmp_path, """\
    faults.fire("server.parse")
    faults.fire("engine.compute")
    """)
        assert run_one(faultpoints, p).findings == []

    def test_rejects_unknown_fire_site(self, tmp_path):
        p = self._project(tmp_path, """\
    faults.fire("server.parse")
    faults.fire("engine.compute")
    faults.fire("server.typo")
    """)
        msgs = [f.message for f in run_one(faultpoints, p).findings]
        assert any("server.typo" in m and "missing from the SITES" in m
                   for m in msgs)

    def test_rejects_dead_catalog_site_and_doc_drift(self, tmp_path):
        p = self._project(
            tmp_path, 'faults.fire("server.parse")\n',
            doc="| `server.parse` | admission | raise |\n"
                "| `server.ghost` | nowhere | raise |\n",
        )
        msgs = [f.message for f in run_one(faultpoints, p).findings]
        assert any("'engine.compute' has no fire() site" in m
                   for m in msgs)
        assert any("'engine.compute' is in SITES but missing" in m
                   for m in msgs)
        assert any("documents site 'server.ghost'" in m for m in msgs)

    def test_rejects_computed_site(self, tmp_path):
        p = self._project(tmp_path, """\
    faults.fire("server.parse")
    faults.fire("engine.compute")
    faults.fire(site_var)
    """)
        msgs = [f.message for f in run_one(faultpoints, p).findings]
        assert any("computed site" in m for m in msgs)


# ---------------------------------------------------------------------------
# suppression + baseline semantics
# ---------------------------------------------------------------------------


class TestBaseline:
    def _project(self, tmp_path):
        make_tree(tmp_path, {
            "pkg/__init__.py": "",
            "pkg/t.py": "import time\na = time.time()\n",
        })
        return project_for(tmp_path)

    def test_active_baseline_demotes_finding(self, tmp_path):
        p = self._project(tmp_path)
        b = Baseline([{
            "rule": "monotonic-clock", "path": "pkg/t.py",
            "reason": "migrating in PR N+1", "expires": "2030-01-01",
        }])
        report = run_rules(p, [monotonic_clock], baseline=b,
                           today=datetime.date(2026, 8, 4))
        assert report.findings == []
        assert len(report.baselined) == 1
        assert not report.failed()

    def test_expired_baseline_fails_again(self, tmp_path):
        p = self._project(tmp_path)
        b = Baseline([{
            "rule": "monotonic-clock", "path": "pkg/t.py",
            "reason": "was due last year", "expires": "2025-06-01",
        }])
        report = run_rules(p, [monotonic_clock], baseline=b,
                           today=datetime.date(2026, 8, 4))
        assert report.findings == []
        assert len(report.expired) == 1
        assert report.failed()

    def test_stale_entry_fails(self, tmp_path):
        p = self._project(tmp_path)
        b = Baseline([{
            "rule": "monotonic-clock", "path": "pkg/other.py",
            "reason": "file was deleted", "expires": "2030-01-01",
        }])
        report = run_rules(p, [monotonic_clock], baseline=b,
                           today=datetime.date(2026, 8, 4))
        assert len(report.unused_baseline) == 1
        assert report.failed()

    def test_unrun_rules_entries_are_not_stale(self, tmp_path):
        # --rules subset: a baseline entry for a rule that did not run
        # cannot be proven stale and must not fail the run
        p = self._project(tmp_path)
        b = Baseline([{
            "rule": "monotonic-clock", "path": "pkg/t.py",
            "reason": "grandfathered", "expires": "2030-01-01",
        }])
        report = run_rules(p, [import_purity], baseline=b,
                           today=datetime.date(2026, 8, 4))
        assert report.unused_baseline == []
        assert not report.failed()

    def test_malformed_baseline_rejected(self):
        with pytest.raises(BaselineError):
            Baseline([{"rule": "x", "path": "y"}])
        with pytest.raises(BaselineError):
            Baseline([{"rule": "x", "path": "y", "reason": "z",
                       "expires": "soonish"}])


# ---------------------------------------------------------------------------
# the repo gate + CLI
# ---------------------------------------------------------------------------


class TestRepoGate:
    def test_repo_is_clean_under_strict(self, tmp_path):
        """The same gate CI runs: every rule over the real repo, strict.
        A finding here means a contract regressed — fix it or baseline
        it with an expiry in analysis/baseline.json."""
        out = tmp_path / "graftcheck.json"
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "graftcheck.py"),
             "--strict", "--json-out", str(out)],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, (
            f"graftcheck --strict failed:\n{proc.stdout}{proc.stderr}"
        )
        payload = json.loads(out.read_text())
        assert payload["failed"] is False
        assert len(payload["rules_run"]) >= 6
        assert payload["files_scanned"] > 80

    def test_cli_rule_subset_and_unknown_rule(self, tmp_path):
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO_ROOT, "tools",
                                          "graftcheck.py"),
             "--rules", "no-such-rule"],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )
        assert proc.returncode == 2
        assert "unknown rule" in proc.stderr

    def test_all_rules_have_unique_ids(self):
        ids = [r.RULE_ID for r in ALL_RULES]
        assert len(ids) == len(set(ids)) == 6


# ---------------------------------------------------------------------------
# regression tests for the behavioral fixes this checker surfaced
# ---------------------------------------------------------------------------


class TestBehavioralFixes:
    def test_stage_scope_duration_survives_wall_clock_jump(
            self, tmp_path, monkeypatch):
        """stage_scope used time.time() for stage durations: an NTP step
        backward mid-stage journaled a negative seconds. Durations now
        come from perf_counter, so a wall jump must not affect them."""
        import time as time_mod

        from machine_learning_replications_tpu.obs import journal

        jumps = iter([1_000_000.0, 999_000.0, 998_000.0, 997_000.0])

        real_time = time_mod.time
        monkeypatch.setattr(
            time_mod, "time",
            lambda: next(jumps, real_time()),
        )
        path = tmp_path / "j.jsonl"
        with journal.RunJournal(path) as jrn:
            journal.set_journal(jrn)
            try:
                with journal.stage_scope("jumpy"):
                    pass
            finally:
                journal.set_journal(None)
        events = [json.loads(line) for line in
                  path.read_text().splitlines()]
        done = [e for e in events if e.get("kind") == "stage_done"]
        assert done and done[0]["seconds"] >= 0.0

    def test_feed_and_reqtrace_families_register_at_import(self):
        """The first scrape of a fresh process must see every family —
        these used to appear only when the first feed/recorder was
        constructed."""
        proc = subprocess.run(
            [sys.executable, "-c", textwrap.dedent("""\
                from machine_learning_replications_tpu.obs import (
                    quality, reqtrace,
                )
                from machine_learning_replications_tpu.obs.registry \\
                    import REGISTRY
                page = REGISTRY.render_prometheus()
                for family in (
                    "quality_feed_dropped_rows_total",
                    "quality_feed_depth",
                    "reqtrace_sampled_total",
                    "reqtrace_dropped_total",
                ):
                    assert f"# TYPE {family}" in page, family
                print("OK")
            """)],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stderr
        assert "OK" in proc.stdout

    def test_score_reader_import_is_jax_free(self):
        """score.reader's parse path is in the jax-free manifest; its
        import used to drag jax in through data/__init__ (and flax
        through persist/__init__ -> models)."""
        proc = subprocess.run(
            [sys.executable, "-c", textwrap.dedent("""\
                import sys
                import machine_learning_replications_tpu.score.reader
                bad = sorted(
                    m for m in sys.modules
                    if m.split(".")[0] in ("jax", "jaxlib", "flax")
                )
                assert not bad, bad
                print("OK")
            """)],
            capture_output=True, text=True, cwd=REPO_ROOT,
        )
        assert proc.returncode == 0, proc.stderr

    def test_uptime_is_monotonic_based(self, monkeypatch):
        """Serving uptime used wall-clock subtraction; a backward NTP
        step made it negative."""
        import time as time_mod

        from machine_learning_replications_tpu.serve.metrics import (
            ServingMetrics,
        )

        m = ServingMetrics()
        monkeypatch.setattr(
            time_mod, "time", lambda: -10_000.0
        )
        assert m.uptime_seconds() >= 0.0
        assert m.snapshot()["uptime_seconds"] >= 0.0
