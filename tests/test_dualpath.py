"""Adaptive dual-path scoring (ISSUE 7): host-vs-device bit parity,
routing policy, padding-aware batch shaping, and the async quality feed.

The acceptance contract: the host fast path shares the device path's math
(same ``impute_select``, same stacked blend) and answers singles
bit-for-bit identically to the device path's single-row program; routing
is a deterministic function of queue depth, in-flight flush state, host
saturation, and request deadline; a flush splits into best-fit ladder
sub-batches with no row lost, duplicated, or reordered and no new
compiles; the quality feed runs off the hot path with every sampled or
shed row counted.
"""

import json
import threading
import time
import urllib.request
import warnings

import numpy as np
import pytest

from machine_learning_replications_tpu.data.examples import (
    EXAMPLE_PATIENT,
    patient_row,
)
from machine_learning_replications_tpu.serve import (
    BucketedPredictEngine,
    HostBusy,
    HostPath,
    HostScorer,
    MicroBatcher,
    PathRouter,
    make_server,
)


@pytest.fixture(scope="module")
def stacking_params():
    from sklearn.ensemble import (
        GradientBoostingClassifier,
        StackingClassifier,
    )
    from sklearn.linear_model import LogisticRegression
    from sklearn.pipeline import make_pipeline
    from sklearn.preprocessing import StandardScaler
    from sklearn.svm import SVC

    from machine_learning_replications_tpu.persist import import_stacking

    rng = np.random.default_rng(11)
    n, f = 250, 17
    X = rng.normal(size=(n, f))
    X[:, :10] = (X[:, :10] > 0.3).astype(float)
    y = (X @ rng.normal(size=f) + rng.normal(size=n) > 0.1).astype(float)
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        clf = StackingClassifier(
            estimators=[
                ("svc", make_pipeline(
                    StandardScaler(),
                    SVC(probability=True, random_state=2020),
                )),
                ("gbc", GradientBoostingClassifier(
                    n_estimators=10, max_depth=1, random_state=2020)),
                ("lg", LogisticRegression()),
            ],
            final_estimator=LogisticRegression(),
        ).fit(X, y)
    return import_stacking(clf)


@pytest.fixture(scope="module")
def query_rows():
    rng = np.random.default_rng(29)
    X = rng.normal(size=(80, 17))
    X[:, :10] = (X[:, :10] > 0.3).astype(float)
    return X


# ---------------------------------------------------------------------------
# host scorer: shared math, bit-for-bit single-row parity
# ---------------------------------------------------------------------------


def test_host_scorer_single_row_parity_bitwise(stacking_params, query_rows):
    """The parity contract the router relies on: for the workload the
    host path serves (single rows), host and device run the same-shape
    program of the same shared composition — results are bit-identical
    across the whole contract row space."""
    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8))
    eng.warmup()
    host = HostScorer(stacking_params, buckets=(1, 8))
    host.warmup()
    for i in range(query_rows.shape[0]):
        h = host.predict(query_rows[i:i + 1])
        d = eng.predict(query_rows[i:i + 1])
        np.testing.assert_array_equal(h, d)
    # small groups share the 8-bucket program: bit-identical too
    np.testing.assert_array_equal(
        host.predict(query_rows[:5]), eng.predict(query_rows[:5])
    )


def test_host_scorer_warmup_pretraces(stacking_params):
    host = HostScorer(stacking_params, buckets=(1, 8))
    assert not host.warm
    host.warmup()
    assert host.warm
    assert host.trace_counts == {1: 1, 8: 1}
    host.predict(patient_row())
    assert host.trace_counts == {1: 1, 8: 1}  # pre-traced: no new compile


# ---------------------------------------------------------------------------
# routing policy: every branch forced
# ---------------------------------------------------------------------------


class _FakeBatcher:
    def __init__(self, depth=0, flushing=False):
        self.queue_depth = depth
        self.flush_in_progress = flushing


class _FakeHost:
    def __init__(self, saturated=False, available=True):
        self.saturated = saturated
        self.available = available


def test_router_decisions_under_forced_state():
    r = PathRouter(_FakeBatcher(), _FakeHost(), burst_depth=2,
                   tight_deadline_s=0.05)
    assert r.decide() == ("host", "idle")
    # queued rows at/above the burst depth coalesce on the device
    r.batcher = _FakeBatcher(depth=2)
    assert r.decide() == ("device", "coalescing")
    r.batcher = _FakeBatcher(depth=5)
    assert r.decide(deadline_s=30.0) == ("device", "coalescing")
    # a tight deadline overrides coalescing — it cannot afford the wait
    assert r.decide(deadline_s=0.05) == ("host", "tight_deadline")
    # a flush mid-compute with an empty queue: host avoids serializing
    # behind the running flush
    r.batcher = _FakeBatcher(depth=0, flushing=True)
    assert r.decide() == ("host", "flush_in_progress")
    # saturation and unavailability always fall back to the device
    r.host = _FakeHost(saturated=True)
    assert r.decide(deadline_s=0.01) == ("device", "host_saturated")
    r.host = _FakeHost(available=False)
    assert r.decide() == ("device", "host_unavailable")
    r.host = None
    assert r.decide() == ("device", "no_host_path")
    with pytest.raises(ValueError):
        PathRouter(_FakeBatcher(), _FakeHost(), burst_depth=0)


def test_host_path_pool_saturation_and_close(stacking_params):
    """HostBusy the instant every slot is taken; slots free as work
    completes; close fails pending work fast."""

    class _SlowScorer:
        warm = True

        def __init__(self):
            self.release = threading.Event()

        def predict(self, X):
            self.release.wait(5.0)
            return X.mean(axis=1)

    scorer = _SlowScorer()
    pool = HostPath(scorer, workers=1)
    try:
        f1 = pool.submit(np.full(17, 2.0))
        time.sleep(0.05)  # the worker claims f1 and blocks
        with pytest.raises(HostBusy):
            pool.submit(np.full(17, 3.0))
        assert pool.saturated
        scorer.release.set()
        assert f1.result(timeout=5.0) == 2.0
        for _ in range(100):
            if not pool.saturated:
                break
            time.sleep(0.01)
        assert not pool.saturated
    finally:
        pool.close()
    with pytest.raises(RuntimeError):
        pool.submit(np.full(17, 1.0))


# ---------------------------------------------------------------------------
# batch shaping: split correctness + compile bound
# ---------------------------------------------------------------------------


def test_plan_batch_shapes(stacking_params):
    eng = BucketedPredictEngine(
        stacking_params, buckets=(1, 8, 32, 64, 128, 256, 512)
    )
    # singles and exact buckets: one chunk, zero pad
    for n in (1, 8, 32, 64, 128, 256, 512):
        assert eng.plan_batch(n) == (n,)
    # the r11 waste cases: 65 → 64+1 (was: pad 447 rows into 512),
    # 200 → 128+64+8 exact (was: pad 312)
    assert eng.plan_batch(65) == (64, 1)
    assert eng.plan_batch(200) == (128, 64, 8)
    # splitting never wins when the padding saved is under the dispatch
    # penalty: tiny batches keep one padded bucket
    assert eng.plan_batch(2) == (8,)
    assert eng.plan_batch(7) == (8,)
    # oversize: whole top-bucket chunks then the shaped remainder
    assert eng.plan_batch(512 + 65) == (512, 64, 1)
    assert eng.plan_batch(0) == ()
    # every chunk is a ladder bucket and the plan covers exactly once
    for n in range(1, 600, 7):
        plan = eng.plan_batch(n)
        assert all(b in eng.buckets for b in plan)
        assert sum(plan) >= n > sum(plan[:-1])
    with pytest.raises(ValueError):
        BucketedPredictEngine(stacking_params, buckets=(1, 8), max_split=0)


def test_split_flush_order_no_loss_no_dup_compile_bound(
    stacking_params, query_rows
):
    """A split flush returns row i's probability at position i (order
    preserved, nothing lost or duplicated — distinct rows prove it), and
    runs only warmed ladder programs (zero new compiles)."""
    from machine_learning_replications_tpu.models import stacking

    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8, 64))
    eng.warmup()
    compiled = dict(eng.trace_counts)
    direct = np.asarray(stacking.predict_proba1(stacking_params, query_rows))
    for n in (9, 10, 65, 73, 80):
        plan = eng.plan_batch(n)
        got = eng.predict(query_rows[:n])
        assert got.shape == (n,)
        # order + identity: every row's answer equals its own direct
        # score (distinct rows → a swap/dup/drop cannot cancel out)
        np.testing.assert_allclose(
            got, direct[:n], rtol=1e-12, atol=1e-15
        )
        assert len(set(direct[:n])) == n  # the oracle really is distinct
        assert sum(plan) >= n
    assert eng.trace_counts == compiled  # per-sub-batch compile bound


def test_batcher_accounts_shaped_padding(stacking_params):
    """The flush's padding metric is the PLAN's pad count, not the old
    single-covering-bucket count."""
    from machine_learning_replications_tpu.serve import ServingMetrics

    eng = BucketedPredictEngine(stacking_params, buckets=(1, 8, 64))
    eng.warmup()
    m = ServingMetrics()
    b = MicroBatcher(eng, max_batch_size=9, max_wait_ms=10_000,
                     max_queue=64, metrics=m)
    try:
        futs = [b.submit(patient_row()[0]) for _ in range(9)]
        for f in futs:
            f.result(timeout=10.0)
        snap = m.padding_waste.snapshot()
        # 9 rows ran as (8, 1): zero pad rows, where the covering 64
        # bucket would have recorded 55
        assert snap["count"] == 1 and snap["sum"] == 0.0
    finally:
        b.close()


# ---------------------------------------------------------------------------
# async quality feed: off-hot-path observation + drop accounting
# ---------------------------------------------------------------------------


def _tiny_profile(rng, n=64):
    from machine_learning_replications_tpu.obs import quality

    X = rng.normal(size=(n, 3))
    return quality.build_reference_profile(X, rng.uniform(size=n))


def test_async_feed_delivers_and_drains():
    from machine_learning_replications_tpu.obs import quality
    from machine_learning_replications_tpu.obs.registry import (
        MetricsRegistry,
    )

    rng = np.random.default_rng(3)
    mon = quality.QualityMonitor(
        _tiny_profile(rng), registry=MetricsRegistry(), min_rows=10,
        window=128,
    )
    feed = quality.AsyncQualityFeed(mon)
    try:
        for _ in range(4):
            feed.observe_batch(
                rng.normal(size=(20, 3)), rng.uniform(size=20)
            )
        assert feed.drain(timeout=5.0)
        stats = feed.stats()
        assert stats["observed_rows"] == 80
        assert stats["dropped_rows"] == 0 and stats["sampled_out_rows"] == 0
        assert mon.snapshot()["rows_total"] == 80
    finally:
        feed.close()


def test_async_feed_sampling_then_shedding_counted():
    """Backpressure accounting: at half capacity incoming batches are
    stride-sampled; at full capacity they shed whole — and the sum of
    observed + sampled_out + dropped equals every row ever offered."""
    from machine_learning_replications_tpu.obs import quality
    from machine_learning_replications_tpu.obs.registry import (
        MetricsRegistry,
    )

    rng = np.random.default_rng(5)
    mon = quality.QualityMonitor(
        _tiny_profile(rng), registry=MetricsRegistry(), min_rows=10,
        window=128,
    )

    gate = threading.Event()
    orig = mon.observe_batch

    def slow_observe(X, p1, members=None):
        gate.wait(10.0)
        return orig(X, p1, members)

    mon.observe_batch = slow_observe
    feed = quality.AsyncQualityFeed(mon, capacity=4, sample_stride=2)
    offered = 0
    try:
        # worker blocks on the first batch; queue then holds up to 4
        for _ in range(8):
            feed.observe_batch(
                rng.normal(size=(10, 3)), rng.uniform(size=10)
            )
            offered += 10
        stats = feed.stats()
        assert stats["sampled_out_rows"] > 0   # half-full → stride sampling
        assert stats["dropped_rows"] > 0       # full → whole-batch shed
        gate.set()
        assert feed.drain(timeout=10.0)
        stats = feed.stats()
        assert (
            stats["observed_rows"] + stats["sampled_out_rows"]
            + stats["dropped_rows"] == offered
        )
        assert mon.snapshot()["rows_total"] == stats["observed_rows"]
    finally:
        gate.set()
        feed.close()


def test_async_feed_quarantines_failing_monitor(tmp_path):
    """A monitor raising on the feed thread quarantines exactly like the
    old in-engine feed: one journaled event, monitor.disable on every
    surface, feed dead (drops counted) until reenable."""
    from machine_learning_replications_tpu.obs import journal, quality
    from machine_learning_replications_tpu.obs.registry import (
        MetricsRegistry,
    )

    rng = np.random.default_rng(7)
    mon = quality.QualityMonitor(
        _tiny_profile(rng), registry=MetricsRegistry(), min_rows=10,
        window=128,
    )
    jrn = journal.RunJournal(tmp_path / "feed.jsonl", command="serve")
    journal.set_journal(jrn)
    feed = quality.AsyncQualityFeed(mon)
    try:
        bad = rng.normal(size=(5, 3))
        bad[0, 0] = np.nan  # observe_batch raises on non-finite rows
        feed.observe_batch(bad, rng.uniform(size=5))
        feed.drain(timeout=5.0)
        assert feed.stats()["dead"]
        assert mon.health()["status"] == "disabled"
        # the poison batch's own rows count as dropped (reason=dead) —
        # they never reached the window
        assert feed.stats()["dropped_rows"] == 5
        # dead feed: subsequent rows are counted as drops, not lost silently
        feed.observe_batch(rng.normal(size=(5, 3)), rng.uniform(size=5))
        feed.drain(timeout=5.0)
        stats = feed.stats()
        assert stats["dropped_rows"] == 10
        # the offered = observed + sampled_out + dropped identity holds
        # through a quarantine
        assert stats["observed_rows"] + stats["sampled_out_rows"] \
            + stats["dropped_rows"] == 10
        # supervisor contract: reenable clears the quarantine
        assert feed.reenable()
        assert mon.health()["status"] != "disabled"
        feed.observe_batch(rng.normal(size=(8, 3)), rng.uniform(size=8))
        assert feed.drain(timeout=5.0)
        assert feed.stats()["observed_rows"] == 8
    finally:
        journal.set_journal(None)
        jrn.close()
        feed.close()
    events = [json.loads(line) for line in open(tmp_path / "feed.jsonl")]
    disabled = [e for e in events if e.get("kind") == "quality_feed_disabled"]
    assert len(disabled) == 1 and "finite" in disabled[0]["error"]


# ---------------------------------------------------------------------------
# end-to-end over HTTP: routing live, parity per path, metrics split
# ---------------------------------------------------------------------------


def _post(url, obj, headers=None, timeout=30.0):
    h = {"Content-Type": "application/json"}
    h.update(headers or {})
    req = urllib.request.Request(
        url + "/predict", data=json.dumps(obj).encode(), headers=h
    )
    with urllib.request.urlopen(req, timeout=timeout) as resp:
        return resp.status, resp.headers, json.loads(resp.read())


def _path_counts(url):
    with urllib.request.urlopen(url + "/metrics", timeout=30) as r:
        page = r.read().decode()
    out = {}
    for line in page.splitlines():
        if line.startswith("serve_path_total{"):
            label, value = line.rsplit(" ", 1)
            out[label.split('"')[1]] = float(value)
    return out


@pytest.fixture()
def routed(stacking_params):
    handle = make_server(
        stacking_params, port=0, buckets=(1, 8), max_wait_ms=2.0,
        max_queue=64, host_path=True,
    ).start_background()
    host, port = handle.address
    yield handle, f"http://{host}:{port}"
    handle.shutdown()


def test_http_single_routes_host_with_bit_parity(routed, stacking_params):
    from machine_learning_replications_tpu.models import stacking

    handle, url = routed
    direct = float(stacking.predict_proba1(stacking_params, patient_row())[0])
    status, headers, body = _post(url, dict(EXAMPLE_PATIENT))
    assert status == 200
    assert headers.get("X-Serve-Path") == "host"
    assert body["probability"] == direct  # bit-for-bit vs the CLI route
    counts = _path_counts(url)
    assert counts["host"] >= 1
    # the trace carries the path annotation + host_compute phase
    with urllib.request.urlopen(url + "/debug/requests?n=8",
                                timeout=30) as r:
        dbg = json.loads(r.read())
    tr = next(t for t in dbg["requests"] if t.get("path") == "host")
    assert "host_compute" in tr["phases"]
    assert "device_compute" not in tr["phases"]
    assert tr["path_reason"] in ("idle", "flush_in_progress")


def test_http_burst_routes_device(routed):
    """Concurrent burst: the admission queue fills, the router coalesces
    into device micro-batches — both paths end up serving traffic."""
    handle, url = routed
    before = _path_counts(url)
    n_threads = 24
    barrier = threading.Barrier(n_threads)
    errors = []

    def one():
        try:
            barrier.wait(10.0)
            for _ in range(4):
                status, _, _ = _post(url, dict(EXAMPLE_PATIENT))
                assert status == 200
        except Exception as exc:  # pragma: no cover - diagnostic
            errors.append(exc)

    threads = [threading.Thread(target=one) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors
    after = _path_counts(url)
    assert after["device"] > before.get("device", 0)  # bursts coalesced
    assert sum(after.values()) - sum(before.values()) == n_threads * 4


def test_http_tight_deadline_header_routes_host(routed):
    handle, url = routed
    status, headers, _ = _post(
        url, dict(EXAMPLE_PATIENT),
        headers={"X-Request-Deadline-Ms": "40"},
    )
    assert status == 200
    assert headers.get("X-Serve-Path") == "host"


def test_host_failure_falls_back_transparently(routed):
    """A one-shot host-path compute fault: the client still gets a
    correct 200 (served by the device fallback), the request counts ONCE
    in serve_requests_total, and the published trace's phases still
    partition the request (the failed attempt's stamps are dropped)."""
    from machine_learning_replications_tpu.resilience import faults

    handle, url = routed
    status, headers, golden_body = _post(url, dict(EXAMPLE_PATIENT))
    assert status == 200

    def requests_total():
        with urllib.request.urlopen(url + "/metrics?format=json",
                                    timeout=30) as r:
            return json.loads(r.read())["requests_total"]

    before = requests_total()
    faults.arm("engine.compute:raise@count=1")
    try:
        status, headers, body = _post(url, dict(EXAMPLE_PATIENT))
    finally:
        faults.reset()
    assert status == 200
    assert body["probability"] == golden_body["probability"]
    assert headers.get("X-Serve-Path") == "device"  # the fallback served
    assert requests_total() == before + 1  # one logical request, once
    with urllib.request.urlopen(url + "/debug/requests?n=16",
                                timeout=30) as r:
        dbg = json.loads(r.read())
    tr = next(
        t for t in dbg["requests"]
        if t.get("path_reason") == "host_error_fallback"
    )
    assert tr["path"] == "device"
    assert "host_compute" not in tr["phases"]  # failed attempt dropped
    total = tr["total_seconds"]
    assert sum(p["seconds"] for p in tr["phases"].values()) <= total + 1e-6


def test_no_host_path_by_default_in_make_server(stacking_params):
    handle = make_server(
        stacking_params, port=0, buckets=(1,), warmup=False,
    ).start_background()
    try:
        host, port = handle.address
        url = f"http://{host}:{port}"
        assert handle.host is None and handle.router is None
        with urllib.request.urlopen(url + "/healthz", timeout=30) as r:
            assert json.loads(r.read())["host_path"] is False
    finally:
        handle.shutdown()


def test_cpu_default_max_batch(stacking_params):
    """Satellite: --max-batch defaults to 64 on the CPU backend (capped
    by the ladder top), keeping saturated flushes in the cheap
    executable; an explicit value still wins."""
    import jax

    handle = make_server(
        stacking_params, port=0, buckets=(1, 8, 128), warmup=False,
    )
    try:
        expected = 64 if jax.default_backend() == "cpu" else 128
        assert handle.batcher._max_batch == expected
    finally:
        handle.shutdown()
    handle = make_server(
        stacking_params, port=0, buckets=(1, 8), warmup=False,
    )
    try:
        assert handle.batcher._max_batch == 8  # capped at the ladder top
    finally:
        handle.shutdown()
    handle = make_server(
        stacking_params, port=0, buckets=(1, 8, 128),
        max_batch_size=100, warmup=False,
    )
    try:
        assert handle.batcher._max_batch == 100
    finally:
        handle.shutdown()


def test_loadgen_artifact_paths_block(routed, tmp_path):
    """Satellite: the loadgen artifact's ``paths`` block records the
    routing split from the echoed X-Serve-Path header."""
    import subprocess
    import sys

    handle, url = routed
    out = tmp_path / "paths.json"
    proc = subprocess.run(
        [sys.executable, "tools/loadgen.py", "--url", url,
         "--mode", "closed", "--concurrency", "2", "--duration", "2",
         "--out", str(out)],
        capture_output=True, text=True, cwd=".",
    )
    assert proc.returncode == 0, proc.stderr
    art = json.loads(out.read_text())
    assert art["paths"] is not None
    assert art["paths"]["source"] == "reply_header"
    counts = art["paths"]["counts"]
    assert sum(counts.values()) == art["n_ok"] > 0
    assert set(counts) <= {"host", "device"}
    for path_name in counts:
        assert art["paths"]["latency_ms"][path_name]["p50"] is not None
