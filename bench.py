#!/usr/bin/env python
"""Driver benchmark: GBDT-ensemble train wall-clock, TPU vs single-CPU sklearn.

Prints ONE JSON line:
  {"metric": ..., "value": <tpu seconds>, "unit": "s", "vs_baseline": <speedup>}

The workload is BASELINE.json config 3 — the reference's
``GradientBoostingClassifier(n_estimators=100, max_depth=1, random_state=2020)``
(``train_ensemble_public.py:45``) — on a Table-S1-matched synthetic cohort
(the reference ships no data; SURVEY.md §6), scaled to ``--rows`` rows
(default 200k, per config 5's scaled-cohort direction). The baseline is
sklearn fitting the identical estimator on the identical matrix on this
host's CPU. ``vs_baseline`` is the wall-clock speedup (baseline / ours);
the run also checks AUC-ROC parity within ±0.005 (BASELINE.json budget)
and fails loudly if violated.

Timing protocol: one compile/warmup fit first (XLA traces once), then the
median of ``--repeats`` end-to-end fits — each timed fit includes host-side
quantile binning, host→device transfer, and the full 100-stage boosting
loop on device (``jax.block_until_ready``). The sklearn baseline is the
median of ``--cpu-repeats`` fits.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
import warnings


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--rows", type=int, default=200_000)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--cpu-repeats", type=int, default=1)
    ap.add_argument(
        "--splitter", choices=("exact", "hist"), default="exact",
        help="split search: 'exact' enumerates every unique-value midpoint "
        "(sklearn BestSplitter semantics); 'hist' caps candidates at 256 "
        "quantile bins (the scalable approximate path)",
    )
    args = ap.parse_args()

    warnings.filterwarnings("ignore")
    import jax
    import numpy as np

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.data.schema import selected_indices
    from machine_learning_replications_tpu.models import gbdt, tree
    from machine_learning_replications_tpu.utils import metrics

    device = jax.devices()[0]
    X, y, _ = make_cohort(n=args.rows, seed=2020)
    X17 = np.ascontiguousarray(X[:, selected_indices()], dtype=np.float32)
    yf = np.asarray(y, dtype=np.float32)

    # --- CPU sklearn baseline (the reference's exact estimator) -----------
    from sklearn.ensemble import GradientBoostingClassifier

    cpu_times = []
    for _ in range(args.cpu_repeats):
        t0 = time.perf_counter()
        sk = GradientBoostingClassifier(
            n_estimators=100, max_depth=1, random_state=2020
        ).fit(X17, y)
        cpu_times.append(time.perf_counter() - t0)
    cpu_s = statistics.median(cpu_times)
    auc_sk = float(metrics.roc_auc(y, sk.predict_proba(X17)[:, 1]))

    # --- TPU-native fit ---------------------------------------------------
    cfg = GBDTConfig(splitter=args.splitter)

    def tpu_fit():
        params, _ = gbdt.fit(X17, yf, cfg)
        jax.block_until_ready(params.value)
        return params

    tpu_fit()  # compile + warm caches
    tpu_times = []
    for _ in range(args.repeats):
        t0 = time.perf_counter()
        params = tpu_fit()
        tpu_times.append(time.perf_counter() - t0)
    tpu_s = statistics.median(tpu_times)
    auc_tpu = float(metrics.roc_auc(y, tree.predict_proba1(params, X17)))

    auc_delta = abs(auc_tpu - auc_sk)
    if auc_delta > 0.005:
        print(
            f"FAIL: AUC parity violated: tpu={auc_tpu:.6f} sklearn={auc_sk:.6f}",
            file=sys.stderr,
        )
        sys.exit(1)

    print(
        f"rows={args.rows} device={device.device_kind} "
        f"sklearn_cpu={cpu_s:.3f}s tpu={tpu_s:.3f}s "
        f"auc sklearn={auc_sk:.6f} tpu={auc_tpu:.6f} (|Δ|={auc_delta:.2e})",
        file=sys.stderr,
    )
    print(
        json.dumps(
            {
                "metric": f"gbdt100_train_wall_clock_{args.rows}rows",
                "value": round(tpu_s, 4),
                "unit": "s",
                "vs_baseline": round(cpu_s / tpu_s, 3),
                "baseline_wall_s": round(cpu_s, 4),
                "auc_delta_vs_sklearn": round(auc_delta, 8),
                "device": str(device.device_kind),
            }
        )
    )


if __name__ == "__main__":
    main()
