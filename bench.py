#!/usr/bin/env python
"""Driver benchmark harness — the five BASELINE.json configs as named entry
points. Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

Configs (``--config``, default 3 — the driver-recorded headline):
  1  single-patient stacked inference, shipped-pickle weights
     (``predict_hf.py`` flow; baseline = closed-form numpy on host CPU)
  2  single decision tree on the HF cohort
     (``GradientBoostingClassifier(n_estimators=1, max_depth=1)`` member)
  3  full 100-stump GradientBoosting ensemble (``train_ensemble_public.py:45``)
  4  5-fold CV sweep over the n_estimators × max_depth grid
     (baseline = sklearn ``GridSearchCV``)
  5  scaled synthetic cohort (default 10M rows), 256-bin hist splitter
     (baseline = sklearn on a subsample, linearly extrapolated — an
     *underestimate* of sklearn's true n·log n cost, so the reported
     speedup is conservative)

The workload data is the Table-S1-matched synthetic cohort (the reference
ships no data; SURVEY.md §6). Every training config checks AUC-ROC parity
with sklearn within ±0.005 (BASELINE.json budget) and fails loudly if
violated. Timing: one warmup (XLA compiles once), then the median of
``--repeats`` end-to-end runs, each blocking on device completion.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time
import warnings


def _median_time(fn, repeats: int, *, warmup: bool = True) -> float:
    """Median wall-clock of ``repeats`` calls. ``warmup`` runs one untimed
    call first (XLA compile); CPU sklearn baselines pass ``warmup=False`` —
    there is nothing to warm and the fits dominate the harness runtime."""
    if warmup:
        fn()
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _emit(payload: dict) -> None:
    print(json.dumps(payload))


def _cohort(rows: int, seed: int = 2020):
    import numpy as np

    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.data.schema import selected_indices

    X, y, _ = make_cohort(n=rows, seed=seed)
    X17 = np.ascontiguousarray(X[:, selected_indices()], dtype=np.float32)
    return X17, np.asarray(y), np.asarray(y, dtype=np.float32)


def bench_inference(args) -> None:
    """Config 1: the predict_hf.py flow — stacked predict_proba from the
    shipped pickle's decoded weights, one patient + a batch."""
    import jax
    import numpy as np

    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.persist import (
        REFERENCE_PKL_PATH,
        decode_pickle,
        import_stacking,
    )

    params = import_stacking(decode_pickle(REFERENCE_PKL_PATH))
    x1 = patient_row().reshape(1, -1)

    predict = jax.jit(stacking.predict_proba1)

    def device_once():
        jax.block_until_ready(predict(params, x1))

    tpu_s = _median_time(device_once, args.repeats * 10)

    # Baseline: the same closed-form math (SURVEY.md §3.4) in numpy on host —
    # the modern stand-in for the reference's sklearn-0.23 predict path,
    # which current sklearn cannot execute from the shipped pickle.
    np_params = jax.tree.map(np.asarray, params)

    def host_once():
        _numpy_stacked_predict(np_params, x1)

    cpu_s = _median_time(host_once, args.repeats * 10)

    prob = float(predict(params, x1)[0])
    _emit({
        "metric": "stacked_inference_latency_1patient",
        "value": round(tpu_s * 1e3, 4),
        "unit": "ms",
        "vs_baseline": round(cpu_s / tpu_s, 3),
        "baseline_ms": round(cpu_s * 1e3, 4),
        "probability_pct": round(100 * prob, 2),
        "device": _device_kind(),
    })


def _numpy_stacked_predict(p, X):
    import numpy as np

    Xs = (X - p.scaler.mean) / p.scaler.scale
    d2 = (
        (Xs * Xs).sum(1)[:, None]
        + (p.svc.support_vectors * p.svc.support_vectors).sum(1)[None, :]
        - 2.0 * Xs @ p.svc.support_vectors.T
    )
    dec = np.exp(-p.svc.gamma * d2) @ p.svc.dual_coef.ravel() + p.svc.intercept
    p_svc = 1.0 / (1.0 + np.exp(p.svc.prob_a * dec + p.svc.prob_b))
    t = p.gbdt
    idx = np.zeros(X.shape[0], dtype=np.int64)
    total = np.zeros(X.shape[0])
    for ti in range(t.feature.shape[0]):
        idx[:] = 0
        for _ in range(t.max_depth):
            f = np.asarray(t.feature)[ti, idx]
            go_left = X[np.arange(X.shape[0]), f] <= np.asarray(t.threshold)[ti, idx]
            idx = np.where(go_left, np.asarray(t.left)[ti, idx], np.asarray(t.right)[ti, idx])
        total += np.asarray(t.value)[ti, idx]
    p_gbc = 1.0 / (1.0 + np.exp(-(float(t.init_raw) + float(t.learning_rate) * total)))
    z = X @ np.asarray(p.logreg.coef).ravel() + float(p.logreg.intercept)
    p_lg = 1.0 / (1.0 + np.exp(-z))
    meta = np.stack([p_svc, p_gbc, p_lg], axis=1)
    zm = meta @ np.asarray(p.meta.coef).ravel() + float(p.meta.intercept)
    return 1.0 / (1.0 + np.exp(-zm))


def bench_gbdt(args, n_estimators: int, metric: str) -> None:
    """Configs 2 & 3: the reference's exact GBDT estimator vs sklearn."""
    import jax

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.models import gbdt, tree
    from machine_learning_replications_tpu.utils import metrics

    X17, y, yf = _cohort(args.rows)

    from sklearn.ensemble import GradientBoostingClassifier

    sk_holder = {}

    def cpu_fit():
        sk_holder["m"] = GradientBoostingClassifier(
            n_estimators=n_estimators, max_depth=1, random_state=2020
        ).fit(X17, y)

    cpu_s = _median_time(cpu_fit, args.cpu_repeats, warmup=False)
    auc_sk = float(metrics.roc_auc(y, sk_holder["m"].predict_proba(X17)[:, 1]))

    cfg = GBDTConfig(splitter=args.splitter, n_estimators=n_estimators)
    holder = {}

    def tpu_fit():
        params, _ = gbdt.fit(X17, yf, cfg)
        jax.block_until_ready(params.value)
        holder["params"] = params

    tpu_s = _median_time(tpu_fit, args.repeats)
    auc_tpu = float(metrics.roc_auc(y, tree.predict_proba1(holder["params"], X17)))
    _check_parity(auc_tpu, auc_sk)

    print(
        f"rows={args.rows} device={_device_kind()} "
        f"sklearn_cpu={cpu_s:.3f}s tpu={tpu_s:.3f}s "
        f"auc sklearn={auc_sk:.6f} tpu={auc_tpu:.6f}",
        file=sys.stderr,
    )
    _emit({
        "metric": metric,
        "value": round(tpu_s, 4),
        "unit": "s",
        "vs_baseline": round(cpu_s / tpu_s, 3),
        "baseline_wall_s": round(cpu_s, 4),
        "auc_delta_vs_sklearn": round(abs(auc_tpu - auc_sk), 8),
        "device": _device_kind(),
    })


def bench_sweep(args) -> None:
    """Config 4: the CV grid sweep vs sklearn GridSearchCV."""
    from machine_learning_replications_tpu.config import SweepConfig
    from machine_learning_replications_tpu.models import sweep as sweep_mod

    X17, y, yf = _cohort(args.rows)
    grid_est = (25, 50, 100)
    grid_depth = (1, 2, 3)
    cfg = SweepConfig(
        n_estimators_grid=grid_est, max_depth_grid=grid_depth, cv_folds=5
    )

    holder = {}

    def ours():
        holder["res"] = sweep_mod.cv_sweep(X17, yf, cfg)

    tpu_s = _median_time(ours, args.repeats)
    res = holder["res"]

    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.model_selection import GridSearchCV

    sk_holder = {}

    def sk_fit():
        sk_holder["gs"] = GridSearchCV(
            GradientBoostingClassifier(random_state=2020),
            {"n_estimators": list(grid_est), "max_depth": list(grid_depth)},
            scoring="roc_auc",
            cv=5,
        ).fit(X17, y)

    cpu_s = _median_time(sk_fit, args.cpu_repeats, warmup=False)
    gs = sk_holder["gs"]
    _check_parity(res.best_mean_auc, float(gs.best_score_))

    _emit({
        "metric": f"cv_sweep_{len(grid_est)}x{len(grid_depth)}_grid_{args.rows}rows",
        "value": round(tpu_s, 4),
        "unit": "s",
        "vs_baseline": round(cpu_s / tpu_s, 3),
        "baseline_wall_s": round(cpu_s, 4),
        "best_auc_delta": round(abs(res.best_mean_auc - float(gs.best_score_)), 8),
        "device": _device_kind(),
    })


def bench_scaled(args) -> None:
    """Config 5: scaled cohort, hist splitter. Baseline extrapolated from a
    sklearn fit on ``--baseline-rows`` (linear in n — conservative for the
    baseline's true n·log n growth)."""
    import jax

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.models import gbdt, tree
    from machine_learning_replications_tpu.utils import metrics

    rows = args.rows if args.rows is not None else 10_000_000
    X17, y, yf = _cohort(rows)

    cfg = GBDTConfig(splitter="hist", n_bins=256)
    holder = {}

    def tpu_fit():
        params, _ = gbdt.fit(X17, yf, cfg)
        jax.block_until_ready(params.value)
        holder["params"] = params

    tpu_s = _median_time(tpu_fit, args.repeats)
    auc_tpu = float(metrics.roc_auc(y, tree.predict_proba1(holder["params"], X17)))

    from sklearn.ensemble import GradientBoostingClassifier

    nb = min(args.baseline_rows, rows)
    t0 = time.perf_counter()
    sk = GradientBoostingClassifier(
        n_estimators=100, max_depth=1, random_state=2020
    ).fit(X17[:nb], y[:nb])
    cpu_sub_s = time.perf_counter() - t0
    cpu_s = cpu_sub_s * (rows / nb)
    auc_sk = float(metrics.roc_auc(y, sk.predict_proba(X17)[:, 1]))
    _check_parity(auc_tpu, auc_sk)

    _emit({
        "metric": f"gbdt100_hist_train_{rows}rows",
        "value": round(tpu_s, 4),
        "unit": "s",
        "vs_baseline": round(cpu_s / tpu_s, 3),
        "baseline_wall_s_extrapolated": round(cpu_s, 2),
        "baseline_measured_rows": nb,
        "throughput_rows_per_s": round(rows / tpu_s, 1),
        "auc_delta_vs_sklearn": round(abs(auc_tpu - auc_sk), 8),
        "device": _device_kind(),
    })


def _check_parity(auc_ours: float, auc_sk: float) -> None:
    if abs(auc_ours - auc_sk) > 0.005:
        print(
            f"FAIL: AUC parity violated: ours={auc_ours:.6f} sklearn={auc_sk:.6f}",
            file=sys.stderr,
        )
        sys.exit(1)


def _device_kind() -> str:
    import jax

    return str(jax.devices()[0].device_kind)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", type=int, choices=(1, 2, 3, 4, 5), default=3)
    ap.add_argument(
        "--rows", type=int, default=None,
        help="cohort rows (default: 200k for configs 1-4, 10M for config 5)",
    )
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--cpu-repeats", type=int, default=1)
    ap.add_argument("--baseline-rows", type=int, default=200_000,
                    help="config 5: sklearn baseline subsample size")
    ap.add_argument(
        "--splitter", choices=("exact", "hist"), default="exact",
        help="split search for configs 2-3: 'exact' enumerates every "
        "unique-value midpoint (sklearn BestSplitter semantics); 'hist' "
        "caps candidates at 256 quantile bins",
    )
    args = ap.parse_args()
    warnings.filterwarnings("ignore")
    if args.rows is None and args.config != 5:
        args.rows = 200_000

    if args.config == 1:
        bench_inference(args)
    elif args.config == 2:
        bench_gbdt(args, 1, f"single_stump_train_{args.rows}rows")
    elif args.config == 3:
        bench_gbdt(args, 100, f"gbdt100_train_wall_clock_{args.rows}rows")
    elif args.config == 4:
        bench_sweep(args)
    else:
        bench_scaled(args)


if __name__ == "__main__":
    main()
