#!/usr/bin/env python
"""Driver benchmark harness — the five BASELINE.json configs, hardened.

Prints exactly ONE JSON line on stdout on EVERY exit path:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}

That line is a COMPACT summary hard-capped at ~1.5 KB (the driver parses a
finite stdout tail: BENCH_r04 recorded rc 0 but ``parsed: null`` because the
old full five-config line overflowed it). The complete payload — per-config
phase timings, probe log, utilization estimates — is written to the file
named by the line's ``detail_file`` key (default ``bench_detail.json`` at
the repo root, ``--detail-out`` to override).

Round-1 failure modes this design answers (VERDICT.md "What's weak" #1):
the 'axon' TPU plugin can hang *forever* at ``import jax`` / backend init,
and the old harness ran minutes of sklearn baselines before first touching
JAX, then died with no JSON at all. Therefore:

  * this orchestrator process NEVER imports jax (nor the package) — all
    device and baseline work runs in subprocesses with hard timeouts;
  * the TPU backend is probed first in short-timeout subprocesses (the hang
    is intermittent — each retry is a fresh interpreter, a fresh chance);
  * if the TPU never comes up, device legs fall back to a *clean* CPU
    environment: the axon sitecustomize only registers its plugin when
    ``PALLAS_AXON_POOL_IPS`` is set, so stripping that var yields an
    interpreter that cannot hang (measured, honest, flagged "degraded");
  * sklearn baseline legs always run in the clean environment — they can
    never be taken down by the TPU tunnel;
  * every exit path — success, parity violation, timeout, crash, budget
    exhaustion — emits the JSON line; parity violations set
    ``"parity_ok": false`` rather than dying silently.

Configs (``--config``; default = all five, headline = config 3):
  1  single-patient stacked inference from the shipped pickle's weights
     (``predict_hf.py`` flow; baseline = same closed-form math in host numpy)
  2  single decision stump on the HF cohort (``GBC(n_estimators=1)``)
  3  full 100-stump GradientBoosting ensemble (``train_ensemble_public.py:45``)
  4  5-fold CV sweep over the n_estimators × max_depth grid vs GridSearchCV
  5  scaled synthetic cohort (default 10M rows) trained through the sharded
     mesh path (``parallel.fit_gbdt_sharded`` over ``make_mesh()`` — a
     1-device mesh is the same code path); baseline = sklearn on
     ``--baseline-rows``, linearly extrapolated (an *underestimate* of
     sklearn's n·log n cost). Both models are scored on the same held-out
     row slice, so the parity check compares like for like (train sizes
     differ by design and are recorded in the artifact).

When the first TPU probe fails, the orchestrator interleaves further probe
attempts (one long 300s try per cycle) with the TPU-independent sklearn
baseline legs until the backend answers or ~60% of ``--budget`` is spent;
every attempt is timestamped into the artifact's ``probe_log``. Configs 3
and 5 additionally report a FLOP/byte utilization estimate (``mfu_pct``,
``hbm_util_pct`` — see ``_utilization`` for the models).

Workload data: the Table-S1-matched synthetic cohort (the reference ships
none; SURVEY.md §6), regenerated deterministically inside each leg from the
same seed. Every training config checks AUC-ROC parity within ±0.005
(BASELINE.json budget). Timing: one warmup (XLA compiles once), then the
median of ``--repeats`` runs, each blocking on device completion; per-phase
wall-clock (``utils.trace.PhaseTimer``) and, for config 3 on TPU, a
Perfetto trace under ``traces/`` plus an on-chip Pallas-vs-XLA histogram
equality check (VERDICT.md next-round items 2 and 8).
"""

from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.abspath(__file__))
PARITY_TOL = 0.005  # BASELINE.json AUC budget

# Rows per config. Config 4's baseline is a 45-fit GridSearchCV on one CPU
# core — it gets a smaller cohort by design.
DEFAULT_ROWS = {1: 1, 2: 1_000_000, 3: 1_000_000, 4: 50_000, 5: 10_000_000}
# CPU-fallback legs run reduced cohorts. r3 post-mortem: at 1M rows the
# degraded path costs 108 s (c2) + 138 s (c5) per device leg plus 3x-repeat
# sklearn baselines, which cannot fit the budget slice that remains after
# the probe loop — the rc=124 driver kill. 200k keeps every CPU leg under
# ~45 s while still exercising the device-binning path
# (>= gbdt.DEVICE_BINNING_MIN_ROWS).
DEGRADED_ROWS = {2: 200_000, 3: 200_000, 5: 1_000_000}
# Budget discipline (VERDICT r3 next-round item 1): all planned work fits
# WORK_FRACTION of --budget — the driver's own clock kills at ~--budget, and
# r3 planned right up to it, so the final JSON line never got printed. The
# probe loop may spend at most PROBE_FRACTION before the run commits to the
# degraded path, so the five CPU legs provably fit the remainder.
WORK_FRACTION = 0.85
PROBE_FRACTION = 0.40
# Healthy device-leg walls (r3, uncontended): c1 ~17s, c2 ~75s, c3 ~100s,
# c4 ~130s, c5 ~200-240s — plus remote-compile variance up to ~2x. The
# timeout is ~3x healthy so ONE tunnel hang cannot eat half the budget
# (r3: a hung c4 leg burned its whole former 900s allowance).
DEVICE_TIMEOUT = {1: 300, 2: 420, 3: 540, 4: 450, 5: 900}
BASELINE_TIMEOUT = {1: 0, 2: 420, 3: 700, 4: 900, 5: 900}

# Chip datasheet anchors for the utilization accounting (VERDICT r2 item 4).
# Peak figures are the bf16 MXU peak and HBM bandwidth; the FLOP/byte models
# used against them are documented in _utilization's docstring.
CHIP_PEAKS = {"TPU v5 lite": {"bf16_tflops": 197.0, "hbm_gbps": 819.0}}

# The driver parses a finite tail of stdout: BENCH_r04 recorded rc 0 with
# ``parsed: null`` because the one ~4 KB five-config line started before the
# tail window did. The stdout line is therefore a compact summary hard-capped
# at SUMMARY_LINE_CAP bytes; the full payload goes to ``detail_file``.
SUMMARY_LINE_CAP = 1500
SUMMARY_CONFIG_FIELDS = ("metric", "value", "unit", "vs_baseline",
                         "vs_baseline_cold", "device", "parity_ok", "rows")


def log(msg: str) -> None:
    print(f"[bench {time.strftime('%H:%M:%S')}] {msg}", file=sys.stderr, flush=True)


def _bench_manifest(args) -> dict:
    """Run-provenance manifest for the artifact (obs.journal.run_manifest —
    git sha, versions, config hash). jax-import-free by that module's
    design, so the orchestrator's never-imports-jax contract holds; the
    config hash binds the artifact to this invocation's knobs."""
    sys.path.insert(0, REPO)
    try:
        from machine_learning_replications_tpu.obs.journal import run_manifest

        knobs = {
            k: v for k, v in sorted(vars(args).items())
            if k not in ("leg", "json_out", "fn")
        }
        return run_manifest(
            command="bench", config_json=json.dumps(knobs, sort_keys=True),
        )
    except Exception as e:  # a manifest must never take down the bench
        return {"kind": "manifest", "error": f"{type(e).__name__}: {e}"}
    finally:
        if sys.path and sys.path[0] == REPO:
            sys.path.pop(0)


# ---------------------------------------------------------------------------
# Orchestrator: environments, probes, subprocess legs
# ---------------------------------------------------------------------------


def _host_cache_tag() -> str:
    """Short fingerprint of the host's CPU feature set. The sandbox can
    migrate between machine types while /tmp survives; XLA:CPU AOT cache
    entries compiled for the old host's features then load with a
    machine-mismatch warning ("could lead to execution errors such as
    SIGILL") — keying the cache dir by the feature set keeps reuse
    same-host only."""
    import hashlib

    try:
        with open("/proc/cpuinfo") as f:
            for line in f:
                # x86 spells it 'flags'; aarch64 spells it 'Features'
                if line.startswith(("flags", "Features")):
                    return hashlib.sha1(line.encode()).hexdigest()[:8]
    except OSError:
        pass
    import platform

    return platform.machine() or "unknown"


def _enable_compile_cache(env: dict, dirname: str) -> None:
    """Point a leg env at a persistent XLA compilation cache so retry
    attempts and repeat legs don't re-pay the compile wall. ``setdefault``
    so an operator-provided cache dir wins; best-effort on mkdir failure."""
    cache = os.path.join(
        tempfile.gettempdir(), f"{dirname}_{_host_cache_tag()}"
    )
    try:
        os.makedirs(cache, exist_ok=True)
        env.setdefault("JAX_COMPILATION_CACHE_DIR", cache)
        env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    except OSError:
        pass


def clean_env() -> dict:
    """Interpreter env that cannot touch the TPU tunnel (shared recipe:
    ``machine_learning_replications_tpu.envsafe`` — importable here because
    the package root only pulls in the pure-python config layer)."""
    sys.path.insert(0, REPO)
    from machine_learning_replications_tpu.envsafe import clean_cpu_env

    env = clean_cpu_env()
    _enable_compile_cache(env, "mlr_tpu_xla_cache")
    return env


def _parse_probe_output(stdout: str) -> str | None:
    """Parse a probe subprocess's stdout into a device-kind string, or None.

    A ``PROBE_OK`` line counts only when the platform is an accelerator: a
    healthy *CPU* backend must read as "TPU down" (VERDICT r3 missing #4 —
    ``PROBE_OK cpu`` would otherwise set degraded=False and launch the
    10M-row config 5 on single-core CPU jax, a guaranteed timeout).
    """
    for line in (stdout or "").splitlines():
        if line.startswith("PROBE_OK"):
            kind = line.split("PROBE_OK", 1)[1].strip()
            platform = kind.split()[0] if kind.split() else ""
            if platform and platform != "cpu":
                return kind
    return None


def probe_tpu(probe_log: list, timeout: int = 150,
              state: "_RunState | None" = None) -> str | None:
    """One attempt to initialize the ambient (TPU) backend in a fresh
    subprocess; outcome appended to ``probe_log`` (timestamped, shipped in
    the artifact so a hostile environment is provable — VERDICT r2 item 1).

    The hang is intermittent, so the *orchestrator* loops this between
    other useful work instead of burning the budget up front. The child is
    registered on ``state`` so a driver SIGTERM mid-probe (likely: the
    probe loop owns up to 40% of the budget) reaps the hung interpreter
    instead of orphaning it on the tunnel.
    """
    code = "import jax; d = jax.devices()[0]; print('PROBE_OK', d.platform, '|', d.device_kind, flush=True)"
    rec = {"t": time.strftime("%H:%M:%S"), "timeout_s": timeout}
    probe_log.append(rec)
    log(f"TPU probe attempt {len(probe_log)} (timeout {timeout}s)")
    t0 = time.perf_counter()
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        cwd=REPO, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    if state is not None:
        state.child = proc
    try:
        stdout, _ = proc.communicate(timeout=timeout)
    except subprocess.TimeoutExpired:
        proc.kill()
        proc.wait()
        rec.update(outcome="timeout", wall_s=round(time.perf_counter() - t0, 1))
        log("probe timed out (backend hang)")
        return None
    finally:
        if state is not None:
            state.child = None
    rec["wall_s"] = round(time.perf_counter() - t0, 1)
    kind = _parse_probe_output(stdout)
    if kind is not None:
        rec.update(outcome="ok", device=kind)
        log(f"TPU backend up: {kind}")
        return kind
    if "PROBE_OK" in (stdout or ""):
        # The backend answered but it is a CPU — the plugin failed over
        # gracefully. That is a DOWN verdict for the accelerator.
        rec.update(outcome="ok_but_cpu")
        log("probe answered with a cpu backend — counting the TPU as down")
        return None
    tail = (stdout or "").strip().splitlines()[-3:]
    rec.update(outcome=f"rc={proc.returncode}")
    log(f"probe rc={proc.returncode}: {' / '.join(tail)}")
    return None


def run_leg(
    leg: str, config: int, env: dict, timeout: int, extra: list[str],
    attempts: int = 2, deadline: float | None = None,
    state: "_RunState | None" = None,
) -> dict:
    """Run one measurement leg in a subprocess; parse its JSON result file.

    The leg's stdout/stderr stream to our stderr (the driver's tail stays
    diagnosable); results travel via a temp file so a crashed leg can never
    corrupt the stdout JSON contract. Returns {"error": ...} on failure.
    Every attempt's timeout is clamped to the orchestrator ``deadline`` so
    retries can never push the whole run past --budget (the no-JSON
    rc=124 failure mode this harness exists to prevent). The live child is
    registered on ``state`` so the SIGTERM flush handler can reap it.
    """
    last_err = "unknown"
    for i in range(attempts):
        if deadline is not None:
            remaining = int(deadline - time.perf_counter())
            if remaining < 30:
                return {"error": f"{last_err}; no budget left for attempt {i + 1}"
                        if last_err != "unknown" else "no budget left"}
            timeout = min(timeout, remaining)
        fd, out_path = tempfile.mkstemp(suffix=".json", prefix=f"bench_{leg}{config}_")
        os.close(fd)
        cmd = [
            sys.executable, os.path.join(REPO, "bench.py"),
            "--leg", leg, "--config", str(config), "--json-out", out_path,
        ] + extra
        log(f"{leg} leg c{config} attempt {i + 1}/{attempts} (timeout {timeout}s)")
        t0 = time.perf_counter()
        proc = subprocess.Popen(
            cmd, cwd=REPO, env=env, stdout=sys.stderr, stderr=sys.stderr,
        )
        if state is not None:
            state.child = proc
        try:
            rc = proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            last_err = f"leg timed out after {timeout}s"
            log(last_err)
            os.unlink(out_path)
            continue
        finally:
            if state is not None:
                state.child = None
        dt = time.perf_counter() - t0
        try:
            with open(out_path) as f:
                payload = json.load(f)
            os.unlink(out_path)
        except (OSError, json.JSONDecodeError):
            payload = None
            os.unlink(out_path)
        if payload is not None and "error" not in payload:
            log(f"{leg} leg c{config} done in {dt:.1f}s")
            return payload
        last_err = (payload or {}).get("error", f"leg rc={rc}, no JSON written")
        log(f"{leg} leg c{config} failed: {last_err}")
    return {"error": last_err}


class _RunState:
    """Everything the signal-flush handler needs to emit a (possibly
    partial) artifact: results land here the moment each config finishes,
    so a driver SIGTERM at any point still yields a JSON line carrying
    every completed measurement (VERDICT r3 next-round item 1a — rc=124
    arrived before the old 'JSON on every exit path' guarantee could fire
    because the payload was only built at the very end)."""

    def __init__(self, args):
        self.args = args
        self.t_start = time.perf_counter()
        self.results: dict[str, dict] = {}
        self.probe_log: list[dict] = []
        self.degraded = True
        self.child: subprocess.Popen | None = None
        self.flushed = False
        # Built up front (not in the signal-flush path: it shells out to
        # git) so every BENCH_* artifact records what produced it.
        self.manifest = _bench_manifest(args)

    def build_payload(self, partial: str | None = None) -> dict:
        args, results = self.args, self.results
        headline_cfg = str(args.config or 3)
        head = results.get(headline_cfg, {"error": "headline config never ran"})
        # parity_ok distinguishes checked-and-passed from never-checked: it
        # is true only when ≥1 config ran its AUC parity check and none
        # failed; parity_checked counts the configs that actually verified.
        checked = [r for r in results.values() if "parity_ok" in r]
        payload = {
            "metric": head.get("metric", f"config{headline_cfg}_failed"),
            "value": head.get("value", 0.0),
            "unit": head.get("unit", "s"),
            "vs_baseline": head.get("vs_baseline", 0.0),
            "device": head.get("device", "unreachable"),
            "parity_ok": bool(checked) and all(r["parity_ok"] for r in checked),
            "parity_checked": len(checked),
            "degraded_cpu_fallback": self.degraded,
            "probe_attempts": len(self.probe_log),
            "probe_log": self.probe_log,
            "wall_s_total": round(time.perf_counter() - self.t_start, 1),
            "manifest": self.manifest,
        }
        if partial:
            payload["partial"] = partial
        if len(results) > 1 or str(args.config or "") not in results:
            payload["configs"] = results
        else:
            payload.update({k: v for k, v in head.items() if k not in payload})
        errors = {c: r["error"] for c, r in results.items() if "error" in r}
        if errors:
            payload["errors"] = errors
        return payload

    def _write_detail(self, payload: dict) -> str | None:
        """Write the full payload to the detail file; return its path, or
        None if the write failed (signal-handler context: best-effort)."""
        path = self.detail_path()
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f, indent=1)
                f.write("\n")
            os.replace(tmp, path)
            return path
        except OSError:
            return None

    def detail_path(self) -> str:
        # abspath: a relative --detail-out resolves against the invoker's
        # cwd, and the summary line must name a location findable from the
        # line alone.
        return os.path.abspath(
            getattr(self.args, "detail_out", None)
            or os.path.join(REPO, "bench_detail.json")
        )

    def summary_line(self, payload: dict, detail_file: str | None) -> str:
        """The ONE stdout line: the driver contract keys plus a per-config
        digest, guaranteed ≤ SUMMARY_LINE_CAP bytes (BENCH_r04's full-payload
        line overflowed the driver's tail/parse window → ``parsed: null``).
        Candidates go from richest to minimal; the first that fits wins."""
        head_keys = ("metric", "value", "unit", "vs_baseline", "device",
                     "parity_ok", "parity_checked", "degraded_cpu_fallback",
                     "probe_attempts", "wall_s_total", "partial")
        head = {k: payload[k] for k in head_keys if k in payload}
        man = payload.get("manifest") or {}
        if man.get("run_id"):
            # Compact provenance on the stdout line itself (~70 bytes);
            # the detail file carries the full manifest.
            head["manifest"] = {
                "run_id": man["run_id"],
                "git_sha": (man.get("git_sha") or "")[:12] or None,
                "config_hash": (man.get("config_hash") or "")[:12] or None,
            }
        if detail_file:
            # Full location, not a basename: a --detail-out outside the repo
            # must still be findable from the line alone.
            try:
                rel = os.path.relpath(detail_file, REPO)
            except ValueError:
                rel = detail_file
            head["detail_file"] = detail_file if rel.startswith("..") else rel
        n_err = sum(1 for r in self.results.values() if "error" in r)
        if n_err:
            head["config_errors"] = n_err

        def digest(err_cap: int, fields: tuple) -> dict:
            out = {}
            for c, rec in sorted(self.results.items()):
                row = {k: rec[k] for k in fields if k in rec}
                if "error" in rec:
                    row["error"] = rec["error"][:err_cap]
                out[c] = row
            return out

        candidates = [
            dict(head, configs=digest(100, SUMMARY_CONFIG_FIELDS)),
            dict(head, configs=digest(40, ("metric", "value", "vs_baseline",
                                           "device", "parity_ok"))),
            dict(head, configs=digest(0, ("value", "vs_baseline", "parity_ok"))),
            head,
        ]
        for cand in candidates:
            line = json.dumps(cand, separators=(",", ":"))
            if len(line) <= SUMMARY_LINE_CAP:
                return line
        # Even bare head overflowed (pathologically long strings): shed keys
        # least-important-first; never slice serialized JSON mid-token.
        for key in ("manifest", "partial", "device", "detail_file", "metric"):
            head.pop(key, None)
            line = json.dumps(head, separators=(",", ":"))
            if len(line) <= SUMMARY_LINE_CAP:
                return line
        return line

    def emit(self, partial: str | None = None) -> int:
        if self.flushed:
            return 1
        self.flushed = True
        payload = self.build_payload(partial)
        # Print the contract line FIRST — the detail write is best-effort
        # file I/O and must never gate the stdout line (a SIGKILL landing
        # during a wedged-filesystem write would otherwise kill the one
        # thing the driver parses). The line names the path we are about
        # to write; a failed write is logged to stderr.
        detail_file = self.detail_path()
        print(self.summary_line(payload, detail_file), flush=True)
        if self._write_detail(payload) is None:
            log(f"detail-file write failed: {detail_file}")
        ok = partial is None and "error" not in \
            self.results.get(str(self.args.config or 3), {"error": "never ran"}) \
            and payload["parity_ok"]
        return 0 if ok else 1


def _install_flush_handlers(state: _RunState) -> None:
    """SIGTERM (the driver's kill) and SIGALRM (our own backstop) both
    flush whatever has been measured so far as the stdout JSON line, reap
    the live leg subprocess, and exit. ``os._exit`` keeps the handler
    re-entrancy-safe: nothing after the flush can corrupt stdout."""

    def flush(signum, frame):
        try:
            child = state.child
            if child is not None and child.poll() is None:
                child.kill()
        except Exception:
            pass
        rc = state.emit(partial=f"flushed on signal {signum} "
                                f"({signal.Signals(signum).name})")
        sys.stdout.flush()
        os._exit(rc if rc else 1)

    signal.signal(signal.SIGTERM, flush)
    signal.signal(signal.SIGALRM, flush)


def orchestrate(args) -> int:
    state = _RunState(args)
    _install_flush_handlers(state)
    t_start = state.t_start
    # All planned work fits in WORK_FRACTION of the budget; the SIGALRM
    # backstop fires just before the driver's own clock would, flushing
    # whatever exists. A clean run cancels the alarm at emit time.
    deadline = t_start + WORK_FRACTION * args.budget
    # The backstop must fire strictly AFTER the planned work deadline (the
    # planner handles its own deadline; the alarm exists for overshoot) and
    # strictly before the driver's kill at ~--budget.
    alarm_s = int(min(args.budget - 5,
                      max(WORK_FRACTION * args.budget + 30, args.budget - 90)))
    signal.alarm(max(60, alarm_s))
    configs = [args.config] if args.config else [3, 1, 2, 5, 4]
    probe_log = state.probe_log
    # Baselines keyed by (config, rows): a record is reusable only for the
    # exact cohort size the surviving device leg ended up running.
    baselines: dict[tuple[int, int], dict] = {}

    def rows_for(c: int, degraded_now: bool) -> int:
        if args.rows:
            return args.rows
        if degraded_now and c in DEGRADED_ROWS:
            return DEGRADED_ROWS[c]
        return DEFAULT_ROWS[c]

    def baseline_args(c: int, rows: int) -> list[str]:
        return ["--rows", str(rows), "--cpu-repeats", str(args.cpu_repeats),
                "--baseline-rows", str(args.baseline_rows)]

    def run_baseline(c: int, rows: int) -> dict:
        key = (c, rows)
        if key not in baselines or "error" in baselines[key]:
            baselines[key] = run_leg(
                "baseline", c, clean_env(), BASELINE_TIMEOUT[c],
                baseline_args(c, rows), deadline=deadline, state=state,
            )
        return baselines[key]

    # --- phase 1: bring up the device backend --------------------------
    # One quick probe; if the backend hangs, keep probing — interleaved
    # with the (TPU-independent) sklearn baseline legs so the wait is never
    # idle — until it answers or PROBE_FRACTION of the budget is gone.
    # Timeouts cycle through one long (300s) attempt per round in case the
    # backend is slow rather than hung. Every attempt lands in probe_log.
    kind = None if args.force_cpu else probe_tpu(probe_log, timeout=150, state=state)
    if kind is None and not args.force_cpu:
        probe_deadline = t_start + PROBE_FRACTION * args.budget
        # Config 1 measures its baseline in-leg. Degraded-size baselines
        # first (the likely outcome when the first probe already failed),
        # most expensive first (c4's GridSearchCV is mode-independent);
        # then the healthy-size records in case the TPU recovers.
        pending = [(c, rows_for(c, True)) for c in (4, 3, 2, 5) if c in configs]
        pending += [
            (c, rows_for(c, False)) for c in (3, 2) if c in configs
            and rows_for(c, False) != rows_for(c, True)
        ]
        timeouts = [150, 300, 150, 150, 300]
        max_probes = 24  # hang-mode attempts are bounded by time anyway;
        #                  this bounds the fast-failure mode (rc!=0 in
        #                  seconds), which additionally backs off below.
        while kind is None and time.perf_counter() < probe_deadline \
                and len(probe_log) < max_probes:
            if pending:
                c, rows = pending.pop(0)
                log(f"probe interleave: baseline leg c{c}@{rows} while TPU is down")
                run_baseline(c, rows)
            elif probe_log[-1].get("wall_s", 0) < 30:
                # fast failure, nothing useful to interleave: back off so a
                # broken-plugin loop can't spin subprocesses for the whole
                # probe window (and flood probe_log)
                time.sleep(min(30, max(0, probe_deadline - time.perf_counter())))
            t = timeouts[(len(probe_log) - 1) % len(timeouts)]
            t = min(t, max(int(probe_deadline - time.perf_counter()), 60))
            kind = probe_tpu(probe_log, timeout=t, state=state)
    state.degraded = degraded = kind is None
    if degraded:
        if not args.force_cpu:
            log(f"TPU unreachable after {len(probe_log)} probes — "
                "device legs fall back to clean-env CPU")
        device_env = clean_env()
    else:
        device_env = dict(os.environ)
        # Same persistent compilation cache the CPU legs get: if the
        # backend supports serialized executables, repeat runs (and the
        # driver's capture after a rehearsal) skip the 20-50 s trace+compile
        # walls, which otherwise dominate value_cold_s; a backend that
        # can't serialize just ignores the cache dir.
        _enable_compile_cache(device_env, "mlr_tpu_xla_cache_device")

    results = state.results
    for c in configs:
        remaining = deadline - time.perf_counter()
        if remaining < 45:
            results[str(c)] = {"error": "skipped: budget exhausted "
                               f"({int(WORK_FRACTION * args.budget)}s work window)"}
            log(f"config {c} skipped — budget exhausted")
            continue

        rows = rows_for(c, degraded)
        # Trace gating lives HERE: the worker's own --trace default is '',
        # so an omitted flag means no tracing in the leg.
        trace = (args.trace or "traces/bench_c3") if (c == 3 and not degraded) else ""

        def leg_args(leg_rows: int, leg_trace: str) -> list[str]:
            return ["--rows", str(leg_rows), "--repeats", str(args.repeats),
                    "--cpu-repeats", str(args.cpu_repeats),
                    "--splitter", args.splitter,
                    "--baseline-rows", str(args.baseline_rows),
                    "--trace", leg_trace]

        dev = run_leg("device", c, device_env, DEVICE_TIMEOUT[c],
                      leg_args(rows, trace), deadline=deadline, state=state)
        if "error" in dev and not degraded:
            # TPU leg failed twice. Re-probe (the tunnel may have dropped
            # mid-run): if the backend answers, one more TPU try; otherwise
            # fall back to a clean-env CPU leg so the artifact still carries
            # a measured number (flagged below).
            tpu_err = dev["error"]
            if probe_tpu(probe_log, timeout=150, state=state):
                log(f"config {c}: TPU leg failed but backend re-probes OK — retrying")
                dev = run_leg("device", c, device_env, DEVICE_TIMEOUT[c],
                              leg_args(rows, trace), attempts=1, deadline=deadline,
                              state=state)
            if "error" in dev:
                log(f"config {c}: TPU leg failed, falling back to clean-env CPU")
                cpu_rows = rows_for(c, degraded_now=True)
                dev = run_leg("device", c, clean_env(), DEVICE_TIMEOUT[c],
                              leg_args(cpu_rows, ""), attempts=1, deadline=deadline,
                              state=state)
                dev["tpu_error"] = tpu_err
                dev["device_fallback"] = "cpu"
                rows = cpu_rows

        if c != 1 and "error" not in dev:
            base = run_baseline(c, rows)
        elif c == 1:
            base = {}  # config 1's numpy baseline is measured inside the leg
        else:
            base = {"error": "skipped: device leg failed"}

        results[str(c)] = combine(c, rows, dev, base)
        log(f"config {c} result: {json.dumps(results[str(c)])[:400]}")

    # --- emit the single JSON line -------------------------------------
    signal.alarm(0)
    return state.emit()


def combine(c: int, rows: int, dev: dict, base: dict) -> dict:
    """Merge a config's device + baseline legs into one result record."""
    if "error" in dev:
        rec = {"error": f"device leg: {dev['error']}"}
        if "tpu_error" in dev:  # keep the original TPU failure diagnosable
            rec["tpu_error"] = dev["tpu_error"]
        return rec
    rec = dict(dev)
    rec.setdefault("unit", "s")
    if c == 1:
        # The reference's sklearn-0.23 predict path cannot execute under a
        # modern sklearn, so config 1's baseline is the same closed-form
        # math in host numpy — labeled so the 12× isn't read as vs-sklearn.
        rec["baseline_kind"] = "numpy_host_closed_form"
        return rec
    if "error" in base:
        rec["baseline_error"] = base["error"]
        rec.setdefault("vs_baseline", 0.0)
        return rec
    cpu_s = base["cpu_s"]
    rec["vs_baseline"] = round(cpu_s / rec["value"], 3)
    rec["baseline_wall_s"] = round(cpu_s, 4)
    if rec.get("value_cold_s"):
        # The warm `value` is the compile-amortized regime; value_cold_s is
        # one cold start (trace+compile+first fit). Publishing both ratios
        # keeps every quoted speedup self-qualifying (VERDICT r3 weak #3).
        rec["vs_baseline_cold"] = round(cpu_s / rec["value_cold_s"], 3)
    for k in ("baseline_measured_rows", "baseline_measured_s", "baseline_repeats"):
        if k in base:
            rec[k] = base[k]
    if rec["vs_baseline"] < 1.0:
        # Never ship a silent sub-1× number (VERDICT r2 weak #2).
        why = ("CPU-fallback leg — single-core JAX vs sklearn's Cython at "
               "this size; the TPU leg is the speedup claim"
               if "cpu" in rec.get("device", "") else
               "slower than the sklearn baseline at this size — see phases_s "
               "for where the time goes")
        rec["note"] = why
    if "auc" in rec and "auc" in base:
        delta = abs(rec["auc"] - base["auc"])
        rec["auc_delta_vs_sklearn"] = round(delta, 8)
        rec["parity_ok"] = bool(delta <= PARITY_TOL)
        if not rec["parity_ok"]:
            log(f"PARITY VIOLATION config {c}: ours={rec['auc']:.6f} "
                f"sklearn={base['auc']:.6f}")
    return rec


# ---------------------------------------------------------------------------
# Legs (run in subprocesses; these DO import jax / sklearn)
# ---------------------------------------------------------------------------


def _median_time(fn, repeats: int, *, warmup: bool = True) -> float:
    import statistics

    if warmup:
        fn()
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        fn()
        times.append(time.perf_counter() - t0)
    return statistics.median(times)


def _cohort(rows: int, seed: int = 2020):
    import numpy as np

    from machine_learning_replications_tpu.data import make_cohort
    from machine_learning_replications_tpu.data.schema import selected_indices

    X, y, _ = make_cohort(n=rows, seed=seed)
    X17 = np.ascontiguousarray(X[:, selected_indices()], dtype=np.float32)
    return X17, np.asarray(y), np.asarray(y, dtype=np.float32)


def _device_kind() -> str:
    import jax

    d = jax.devices()[0]
    return f"{d.platform}:{d.device_kind}"


def _is_tpu() -> bool:
    import jax

    d = jax.devices()[0]
    return d.platform in ("tpu", "axon") or "tpu" in d.device_kind.lower()


def _cache_entry_count() -> int:
    cache_dir = os.environ.get("JAX_COMPILATION_CACHE_DIR")
    if not cache_dir:
        return 0
    try:
        return len([f for f in os.listdir(cache_dir) if f.endswith("-cache")])
    except OSError:
        return 0


def device_leg(args) -> dict:
    log(f"device leg c{args.config} starting (rows={args.rows})")
    entries_at_start = _cache_entry_count()
    import jax

    from machine_learning_replications_tpu.obs import jaxmon

    # Compile accounting for the artifact: how many XLA programs this leg
    # built and what the compile wall cost — the number that separates a
    # genuinely slow trainer from a recompile regression.
    jaxmon.install()
    log(f"jax backend up: {_device_kind()}")
    if args.config == 1:
        rec = device_leg_inference(args)
    elif args.config in (2, 3):
        rec = device_leg_gbdt(args, 1 if args.config == 2 else 100)
    elif args.config == 4:
        rec = device_leg_sweep(args)
    else:
        rec = device_leg_scaled(args)
    rec["jax_compiles"] = jaxmon.compile_count()
    rec["jax_compile_seconds"] = round(jaxmon.compile_seconds(), 3)
    if os.environ.get("JAX_COMPILATION_CACHE_DIR"):
        # With a persistent compile cache, *_cold_s on a PREWARMED run is
        # "first fit incl. cache-hit compile", not a from-scratch trace+
        # compile. ``prewarmed`` records whether the cache had entries when
        # this leg started — the field that separates a genuinely cold
        # artifact from a cache-warm repeat (the phases_s compile entries
        # then show what this run actually paid).
        rec["compile_cache"] = {
            "dir_set": True,
            "prewarmed": bool(entries_at_start),
            "entries_at_start": entries_at_start,
            "entries_now": _cache_entry_count(),
        }
    return rec


def device_leg_inference(args) -> dict:
    """Config 1: predict_hf.py flow — stacked predict_proba from the shipped
    pickle's decoded weights; baseline = the same closed-form math (SURVEY.md
    §3.4) in host numpy (the modern stand-in for the reference's sklearn-0.23
    predict path, which current sklearn cannot execute from the pickle)."""
    import jax
    import numpy as np

    from machine_learning_replications_tpu.data.examples import patient_row
    from machine_learning_replications_tpu.models import stacking
    from machine_learning_replications_tpu.persist import (
        REFERENCE_PKL_PATH,
        decode_pickle,
        import_stacking,
    )

    params = import_stacking(decode_pickle(REFERENCE_PKL_PATH))
    x1 = patient_row().reshape(1, -1)
    predict = jax.jit(lambda p, x: stacking.predict_proba1(p, x)[0])
    # End-to-end like the reference flow (predict_hf.py PRINTS the number:
    # the host must receive it): the timed unit is patient-row in →
    # probability scalar on host, including the device→host result fetch.
    # Device-only completion is recorded alongside for diagnosis — on a
    # tunneled backend the fetch can dominate, and hiding it would make
    # the latency claim unusable for a real client.
    #
    # Every timed iteration gets a slightly-jittered patient row: this
    # backend memoizes repeated identical dispatches, so timing the SAME
    # row over and over measures the memo table, not a fresh
    # dispatch+fetch (ADVICE r3 item 3; memory: dispatch memoization).
    n_timed = args.repeats * 10

    def make_cycler(arr):
        """Cycle host-side (numpy) inputs so every timed call is a fresh
        dispatch — device-resident pools would add an eager index dispatch
        to each repeat on this backend."""
        cur = {"i": 0}

        def nxt():
            i = cur["i"]
            cur["i"] = (i + 1) % arr.shape[0]
            return arr[i]

        return nxt

    jrng = np.random.default_rng(2020)
    probes_np = (
        x1[None, :, :]
        + jrng.normal(0, 1e-3, size=(2 * (n_timed + 1), 1, x1.shape[1]))
    ).astype(np.float32)
    next_probe = make_cycler(probes_np)

    e2e_s = _median_time(lambda: float(predict(params, next_probe())), n_timed)
    dev_s = _median_time(
        lambda: jax.block_until_ready(predict(params, next_probe())), n_timed
    )
    np_params = jax.tree.map(np.asarray, params)
    cpu_s = _median_time(
        lambda: _numpy_stacked_predict(np_params, next_probe()), n_timed
    )
    prob = float(predict(params, x1))

    # Pure link round trip: the smallest possible send+dispatch+fetch (one
    # scalar through a jitted add), host-jittered per repeat like the
    # patient rows. Same timing basis as e2e_s (host in -> host out), so
    # e2e minus this estimates what a colocated client would see
    # (VERDICT r3 weak #4 — makes the honest sub-1x number
    # self-explaining in the artifact).
    import jax.numpy as jnp

    tiny = jax.jit(lambda v: v + 1.0)
    next_scalar = make_cycler(
        np.arange(2 * (n_timed + 1), dtype=np.float32)
    )
    rtt_s = _median_time(lambda: float(tiny(next_scalar())), n_timed)

    # Batch regime: the same stacked graph over a cohort-scale matrix.
    # Single-patient offload is round-trip-bound by construction (a
    # 17-feature closed form cannot amortize any link), so the artifact
    # carries the throughput point where a device makes sense at all.
    nb = 100_000
    rng = np.random.default_rng(2020)
    Xb = (x1 + rng.normal(0, 0.05, size=(nb, x1.shape[1]))).astype(np.float32)
    predict_b = jax.jit(stacking.predict_proba1)
    # Distinct device-resident batches per timed repeat — same
    # anti-memoization rationale as the single-patient loop above.
    n_batches = args.repeats + 1  # one per timed dispatch + warmup: no
    #                                   wrap, so no dispatch ever repeats
    Xb_devs = [
        jax.device_put(jnp.asarray(Xb + np.float32(1e-4 * i)))
        for i in range(n_batches)
    ]
    bcursor = {"i": 0}

    def next_batch():
        i = bcursor["i"]
        bcursor["i"] = (i + 1) % n_batches
        return Xb_devs[i]

    batch_s = _median_time(
        lambda: float(jnp.sum(predict_b(params, next_batch()))), args.repeats
    )
    Xb64 = Xb.astype(np.float64)  # numpy does not memoize; no jitter needed
    cpu_batch_s = _median_time(
        lambda: _numpy_stacked_predict(np_params, Xb64).sum(), args.repeats
    )

    rec = {
        "metric": "stacked_inference_latency_1patient",
        "value": round(e2e_s * 1e3, 4),
        "unit": "ms",
        "vs_baseline": round(cpu_s / e2e_s, 3),
        "baseline_ms": round(cpu_s * 1e3, 4),
        "device_only_ms": round(dev_s * 1e3, 4),
        "link_rtt_ms": round(rtt_s * 1e3, 4),
        "latency_colocated_est_ms": round(max(e2e_s - rtt_s, 0.0) * 1e3, 4),
        "probability_pct": round(100 * prob, 2),
        "batch100k_rows_per_s": round(nb / batch_s, 1),
        "batch100k_vs_numpy": round(cpu_batch_s / batch_s, 3),
        "device": _device_kind(),
    }
    if e2e_s > cpu_s:
        rec["note"] = (
            "single-patient latency is host-link round-trip-bound "
            "(~70 ms on the tunneled backend; the predict itself is "
            "device_only-dominated by the same RTT) — see "
            "batch100k_* for the throughput regime"
        )
    return rec


def _numpy_stacked_predict(p, X):
    import numpy as np

    Xs = (X - p.scaler.mean) / p.scaler.scale
    d2 = (
        (Xs * Xs).sum(1)[:, None]
        + (p.svc.support_vectors * p.svc.support_vectors).sum(1)[None, :]
        - 2.0 * Xs @ p.svc.support_vectors.T
    )
    dec = np.exp(-p.svc.gamma * d2) @ p.svc.dual_coef.ravel() + p.svc.intercept
    p_svc = 1.0 / (1.0 + np.exp(p.svc.prob_a * dec + p.svc.prob_b))
    t = p.gbdt
    idx = np.zeros(X.shape[0], dtype=np.int64)
    total = np.zeros(X.shape[0])
    for ti in range(t.feature.shape[0]):
        idx[:] = 0
        for _ in range(t.max_depth):
            f = np.asarray(t.feature)[ti, idx]
            go_left = X[np.arange(X.shape[0]), f] <= np.asarray(t.threshold)[ti, idx]
            idx = np.where(go_left, np.asarray(t.left)[ti, idx], np.asarray(t.right)[ti, idx])
        total += np.asarray(t.value)[ti, idx]
    p_gbc = 1.0 / (1.0 + np.exp(-(float(t.init_raw) + float(t.learning_rate) * total)))
    z = X @ np.asarray(p.logreg.coef).ravel() + float(p.logreg.intercept)
    p_lg = 1.0 / (1.0 + np.exp(-z))
    meta = np.stack([p_svc, p_gbc, p_lg], axis=1)
    zm = meta @ np.asarray(p.meta.coef).ravel() + float(p.meta.intercept)
    return 1.0 / (1.0 + np.exp(-zm))


def _utilization(dev_s: float, n: int, F: int, stages: int,
                 mode: str = "sorted", n_bins: int = 256) -> dict:
    """Hardware-efficiency accounting (VERDICT r2 item 4: a speedup claim
    needs a utilization denominator). Two per-stage models:

    ``mode='sorted'`` — the replicated-sorted-layout trainer (now only
    the sub-100k host-binned regimes): ~6 dense passes over the ``[F, n]``
    layout ⇒ ~20 flops and ~33 bytes per element per stage;
    bandwidth-bound by design (intensity ≈ 0.6 flop/byte), so
    hbm_util_pct is the number to watch. The r5 trace read
    (docs/SCALING.md "Roofline") showed most of its per-stage time in
    pad/reshape data formatting, which is why the hot paths moved off
    this design.

    ``mode='hist_mxu'`` — the r5 unsorted histogram formulation (the
    fused configs 2/3 fit AND the sharded config-5 trainer): per stage
    one u8 ``[n, F]`` bin-matrix read plus ~9 ``[n]`` f32 passes ⇒
    ≈ n·(F + 36) bytes, and a one-hot MXU contraction of 2 stats ⇒
    ≈ 4·n·F·B + 25·n flops. Intensity flips to ~300 flop/byte — the
    stage is MXU-bound, so mfu_pct is the honest gauge and hbm_util_pct
    the small one.
    """
    import jax

    d = jax.devices()[0]
    peaks = CHIP_PEAKS.get(d.device_kind)
    if mode == "hist_mxu":
        flops = (4.0 * n * F * n_bins + 25.0 * n) * stages
        bytes_ = n * (F + 36.0) * stages
    else:
        flops = 20.0 * n * F * stages
        bytes_ = 33.0 * n * F * stages
    rec = {
        "stage_model": mode,
        "flops_est": flops,
        "bytes_est": bytes_,
        "arithmetic_intensity": round(flops / bytes_, 3),
    }
    if peaks and dev_s > 0:
        rec["mfu_pct"] = round(100.0 * flops / (dev_s * peaks["bf16_tflops"] * 1e12), 4)
        rec["hbm_util_pct"] = round(100.0 * bytes_ / (dev_s * peaks["hbm_gbps"] * 1e9), 2)
        rec["peak_model"] = f"{d.device_kind}: {peaks['bf16_tflops']} bf16 TFLOPS, {peaks['hbm_gbps']} GB/s"
    return rec


def device_leg_gbdt(args, n_estimators: int) -> dict:
    """Configs 2 & 3: the reference's GBDT estimator on device, with
    per-phase wall-clock; config 3 on TPU additionally captures a Perfetto
    trace and runs the on-chip Pallas-vs-XLA histogram equality check."""
    import jax

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.models import gbdt, tree
    from machine_learning_replications_tpu.utils import metrics
    from machine_learning_replications_tpu.utils.trace import PhaseTimer, device_trace

    timer = PhaseTimer()
    with timer.phase("make_cohort"):
        X17, y, yf = _cohort(args.rows)
    cfg = GBDTConfig(splitter=args.splitter, n_estimators=n_estimators)
    import jax.numpy as jnp

    # One-time host→device staging, reported separately (device-resident
    # train data, as sklearn's baseline fit gets RAM-resident data; the
    # tunnel link can run as slow as ~18 MB/s, which would otherwise
    # dominate the fit). Only when the fit actually bins on device —
    # handing a device array to the host-binning regimes (exact splitter,
    # small rows) would make every timed repeat pull X back through the
    # same slow link instead.
    # fit() routes one-shot stumps (n_estimators=1 at device-binning
    # scale) through the threaded host engine (gbdt._fit_stump_host): no
    # XLA compile, no h2d of a 68 MB matrix through the tunnel for
    # ~0.5 s of work, and no device in the loop at all — the leg must
    # keep X host-resident AND report the engine honestly below.
    host_stump = n_estimators == 1 and gbdt.uses_fused_hist1(cfg, args.rows)
    if cfg.splitter == "hist" and args.rows >= gbdt.DEVICE_BINNING_MIN_ROWS \
            and not host_stump:
        with timer.phase("h2d_transfer") as ph:
            X17_d = ph.block(jax.device_put(jnp.asarray(X17)))
            yf_d = ph.block(jax.device_put(jnp.asarray(yf)))
    else:
        X17_d, yf_d = X17, yf
    if not host_stump:
        # Recorded for the phase breakdown only — the timed fit below
        # re-bins from scratch so the measurement covers the same
        # end-to-end work as the sklearn baseline's fit() (which includes
        # its presort). The host-stump leg skips this: its fit derives
        # candidates itself and never touches the device.
        with timer.phase("binning") as ph:
            ph.block(gbdt.default_bins(X17_d, cfg).binned)

    holder = {}

    def fit_once():
        params, _ = gbdt.fit(X17_d, yf_d, cfg)
        jax.block_until_ready(params.value)
        holder["params"] = params

    with timer.phase("fit_warmup_compile"):
        fit_once()
    with timer.phase("fit_timed"):
        dev_s = _median_time(fit_once, args.repeats, warmup=False)
    predict = jax.jit(tree.predict_proba1)
    auc_fn = jax.jit(metrics.roc_auc)
    with timer.phase("predict_auc") as ph:
        auc = float(ph.block(auc_fn(jnp.asarray(y), predict(holder["params"], X17_d))))

    cold_s = timer.seconds.get("fit_warmup_compile", 0.0)
    rec = {
        "metric": (
            f"single_stump_train_{args.rows}rows" if n_estimators == 1
            else f"gbdt100_train_wall_clock_{args.rows}rows"
        ),
        "value": round(dev_s, 4),
        "value_cold_s": round(cold_s, 4),
        "unit": "s",
        "auc": auc,
        "splitter": args.splitter,
        # the host-stump engine never touches the accelerator: the device
        # column must say so, and chip-peak utilization would be fiction
        "device": "host:numpy_stump" if host_stump else _device_kind(),
        "phases_s": {k: round(v, 4) for k, v in timer.seconds.items()},
    }
    if not host_stump:
        rec.update(_utilization(
            dev_s, args.rows, X17.shape[1], n_estimators,
            # same predicate fit() uses to pick the fused unsorted path
            mode=("hist_mxu" if gbdt.uses_fused_hist1(cfg, args.rows)
                  else "sorted"),
            n_bins=cfg.n_bins,
        ))
    else:
        rec["engine"] = (
            "host numpy single-stump (gbdt._fit_stump_host): one-shot "
            "fits skip XLA entirely, so cold == warm — no compile wall"
        )
    if n_estimators == 1 and cold_s > 5 * dev_s:
        # Legacy device-path regime note (only reachable if the host
        # engine is bypassed): the wall is one-time trace+compile.
        rec["compile_bound"] = True
        rec["marginal_stage_s"] = round(dev_s, 4)
        rec["note_compile"] = (
            "n_estimators=1 at this size is compile-bound: value is the "
            "amortized warm fit (the marginal cost of a stump once the "
            "program exists); value_cold_s is trace+compile+first fit"
        )

    if args.trace and n_estimators > 1:
        trace_dir = os.path.join(REPO, args.trace)
        try:
            os.makedirs(trace_dir, exist_ok=True)
            with device_trace(trace_dir):
                fit_once()
            rec["trace_dir"] = args.trace
            log(f"profiler trace written to {trace_dir}")
        except Exception as e:  # profiling is best-effort on the plugin backend
            rec["trace_error"] = f"{type(e).__name__}: {e}"

    if _is_tpu() and n_estimators > 1:
        for attempt in (1, 2):  # remote-compile service flakes transiently
            try:
                rec["pallas_onchip"] = pallas_onchip_check(X17, yf)
                break
            except Exception as e:
                rec["pallas_onchip"] = {"error": f"{type(e).__name__}: {e}"}
                if attempt == 1:  # keep the first flake diagnosable
                    rec["pallas_onchip_first_error"] = f"{type(e).__name__}: {e}"
    return rec


def pallas_onchip_check(X17, yf) -> dict:
    """On-TPU correctness + timing of the Pallas histogram kernel against the
    XLA segment_sum path at real sizes (VERDICT.md item 8: the kernel had
    only ever run in interpret mode on CPU; the Mosaic lowering and VMEM
    accumulation pattern are exactly what this validates)."""
    import numpy as np
    import jax
    import jax.numpy as jnp

    from machine_learning_replications_tpu.ops import binning, histogram
    from machine_learning_replications_tpu.ops.pallas_histogram import (
        node_histograms_pallas,
    )

    bins = binning.bin_features(X17, 256)
    n = X17.shape[0]
    K = 8  # a depth-3 level
    rng = np.random.default_rng(0)
    node = jnp.asarray(rng.integers(0, K, n, dtype=np.int32))
    g = jnp.asarray(yf - 0.5)
    h = jnp.asarray(0.25 * np.ones(n, np.float32))
    binned = jnp.asarray(bins.binned)

    # Arrays passed as jit ARGUMENTS (not closed-over constants) so XLA
    # cannot constant-fold the measured computation away.
    run_p = jax.jit(node_histograms_pallas, static_argnums=(4, 5))
    run_x = jax.jit(histogram.node_histograms, static_argnums=(4, 5))
    hp = jax.block_until_ready(run_p(binned, node, g, h, K, bins.max_bins))
    hx = jax.block_until_ready(run_x(binned, node, g, h, K, bins.max_bins))
    for a, b, name in zip(hp, hx, ("grad", "hess", "count")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-2,
            err_msg=f"pallas vs xla histogram mismatch: {name}",
        )
    t_p = _median_time(
        lambda: jax.block_until_ready(run_p(binned, node, g, h, K, bins.max_bins)), 5
    )
    t_x = _median_time(
        lambda: jax.block_until_ready(run_x(binned, node, g, h, K, bins.max_bins)), 5
    )
    return {
        "equal": True,
        "rows": n,
        "nodes": K,
        "pallas_ms": round(t_p * 1e3, 3),
        "xla_ms": round(t_x * 1e3, 3),
        "kernel_speedup_vs_xla": round(t_x / t_p, 2),
    }


def device_leg_sweep(args) -> dict:
    """Config 4: the staged-prediction CV grid sweep on device."""
    from machine_learning_replications_tpu.config import SweepConfig
    from machine_learning_replications_tpu.models import sweep as sweep_mod

    X17, y, yf = _cohort(args.rows)
    cfg = SweepConfig(
        n_estimators_grid=(25, 50, 100), max_depth_grid=(1, 2, 3), cv_folds=5
    )
    holder = {}

    def ours():
        holder["res"] = sweep_mod.cv_sweep(X17, yf, cfg)

    # On the CPU fallback a full sweep is tens of seconds; warmup + one
    # timed run keeps the leg inside its budget clamp (r3: the c4 CPU leg
    # blew a 72s clamp doing 1+3 sweeps).
    reps = args.repeats if _is_tpu() else 1
    dev_s = _median_time(ours, reps)
    res = holder["res"]
    return {
        "metric": f"cv_sweep_3x3_grid_{args.rows}rows",
        "value": round(dev_s, 4),
        "repeats_used": reps,
        "unit": "s",
        "auc": float(res.best_mean_auc),
        "best_cell": [res.best_max_depth, res.best_n_estimators],
        "device": _device_kind(),
    }


def device_leg_scaled(args) -> dict:
    """Config 5: scaled cohort through the real sharded path — mesh over all
    available devices, rows sharded on the 'data' axis through the
    ``fit_gbdt_sharded`` dispatch (sorted-stump trainer with device binning
    at this depth/size; a 1-device mesh is the same code path). Held-out
    scoring runs row-sharded too (VERDICT r2 item 5)."""
    import jax
    import jax.numpy as jnp

    from machine_learning_replications_tpu.config import GBDTConfig
    from machine_learning_replications_tpu.models import tree
    from machine_learning_replications_tpu.parallel import fit_gbdt_sharded, make_mesh
    from machine_learning_replications_tpu.parallel.rowwise import apply_rows_sharded
    from machine_learning_replications_tpu.utils import metrics
    from machine_learning_replications_tpu.utils.trace import PhaseTimer

    timer = PhaseTimer()
    rows = args.rows
    holdout = min(100_000, rows // 10)
    with timer.phase("make_cohort"):
        X17, y, yf = _cohort(rows)
    Xtr, ytr = X17[: rows - holdout], yf[: rows - holdout]
    Xte, yte = X17[rows - holdout:], y[rows - holdout:]

    mesh = make_mesh()
    cfg = GBDTConfig(splitter="hist", n_bins=256)
    # One-time host→device staging, reported separately: the timed fit
    # starts from device-resident data the way sklearn's starts from
    # RAM-resident data (the tunnel moves ~18 MB/s — at 10M rows re-paying
    # ~38 s of transfer per repeat would measure the link, not the trainer).
    with timer.phase("h2d_transfer") as ph:
        Xtr_d = ph.block(jax.device_put(jnp.asarray(Xtr)))
        ytr_d = ph.block(jax.device_put(jnp.asarray(ytr)))
    holder = {}

    def fit_once():
        params, _ = fit_gbdt_sharded(mesh, Xtr_d, ytr_d, cfg)
        jax.block_until_ready(params.value)
        holder["params"] = params

    with timer.phase("fit_warmup_compile"):
        fit_once()
    with timer.phase("fit_timed"):
        dev_s = _median_time(fit_once, args.repeats, warmup=False)
    with timer.phase("predict_auc") as ph:
        proba = apply_rows_sharded(
            mesh, tree.predict_proba1, holder["params"], Xte
        )
        auc = float(ph.block(jax.jit(metrics.roc_auc)(jnp.asarray(yte), proba)))
    return {
        "metric": f"gbdt100_hist_train_{rows}rows_sharded",
        "value": round(dev_s, 4),
        "value_cold_s": round(timer.seconds.get("fit_warmup_compile", 0.0), 4),
        "unit": "s",
        "auc": auc,
        "train_rows": rows - holdout,
        "holdout_rows": holdout,
        "mesh": {k: int(v) for k, v in zip(mesh.axis_names, mesh.devices.shape)},
        "throughput_rows_per_s": round((rows - holdout) / dev_s, 1),
        "device": _device_kind(),
        "phases_s": {k: round(v, 4) for k, v in timer.seconds.items()},
        # r5: the sharded stump trainer uses the same unsorted histogram
        # stage as the fused single-device path
        **_utilization(dev_s, rows - holdout, X17.shape[1], cfg.n_estimators,
                       mode="hist_mxu", n_bins=cfg.n_bins),
    }


def baseline_leg(args) -> dict:
    """sklearn CPU baselines — always in the clean env, never on the TPU."""
    log(f"baseline leg c{args.config} starting (rows={args.rows})")
    import warnings

    warnings.filterwarnings("ignore")
    if args.config in (2, 3):
        return baseline_leg_gbdt(args, 1 if args.config == 2 else 100)
    if args.config == 4:
        return baseline_leg_sweep(args)
    if args.config == 5:
        return baseline_leg_scaled(args)
    raise ValueError(f"no baseline leg for config {args.config}")


def baseline_leg_gbdt(args, n_estimators: int) -> dict:
    from sklearn.ensemble import GradientBoostingClassifier

    from machine_learning_replications_tpu.utils import metrics

    X17, y, _ = _cohort(args.rows)
    holder = {}

    def fit():
        holder["m"] = GradientBoostingClassifier(
            n_estimators=n_estimators, max_depth=1, random_state=2020
        ).fit(X17, y)

    # Repeats are for variance at the 1.0x boundary, which only matters for
    # sub-minute fits; at >=500k rows one 100-stump sklearn fit is 35-80 s
    # and the 3x median would alone blow the budget slice (r3 post-mortem).
    reps = args.cpu_repeats if args.rows < 500_000 or n_estimators == 1 else 1
    cpu_s = _median_time(fit, reps, warmup=False)
    auc = float(metrics.roc_auc(y, holder["m"].predict_proba(X17)[:, 1]))
    return {"cpu_s": cpu_s, "auc": auc, "baseline_repeats": reps}


def baseline_leg_sweep(args) -> dict:
    from sklearn.ensemble import GradientBoostingClassifier
    from sklearn.model_selection import GridSearchCV

    X17, y, _ = _cohort(args.rows)
    holder = {}

    def fit():
        holder["gs"] = GridSearchCV(
            GradientBoostingClassifier(random_state=2020),
            {"n_estimators": [25, 50, 100], "max_depth": [1, 2, 3]},
            scoring="roc_auc", cv=5,
        ).fit(X17, y)

    # One run IS 45 fits — internally averaged already; repeating the whole
    # GridSearchCV three times (135 fits, ~290 s at 50k rows) was the
    # single most expensive baseline in the r3 budget blowout.
    cpu_s = _median_time(fit, 1, warmup=False)
    return {"cpu_s": cpu_s, "auc": float(holder["gs"].best_score_),
            "baseline_repeats": 1}


def baseline_leg_scaled(args) -> dict:
    """sklearn on a subsample of the same train slice, linearly extrapolated
    (conservative: sklearn's presort is n·log n); scored on the same held-out
    slice the device leg uses."""
    from sklearn.ensemble import GradientBoostingClassifier

    from machine_learning_replications_tpu.utils import metrics

    rows = args.rows
    holdout = min(100_000, rows // 10)
    X17, y, _ = _cohort(rows)
    train_rows = rows - holdout
    nb = min(args.baseline_rows, train_rows)
    t0 = time.perf_counter()
    m = GradientBoostingClassifier(
        n_estimators=100, max_depth=1, random_state=2020
    ).fit(X17[:nb], y[:nb])
    measured = time.perf_counter() - t0
    auc = float(metrics.roc_auc(y[train_rows:], m.predict_proba(X17[train_rows:])[:, 1]))
    return {
        "cpu_s": measured * (train_rows / nb),
        "auc": auc,
        "baseline_measured_rows": nb,
        "baseline_measured_s": round(measured, 4),
    }


# ---------------------------------------------------------------------------
# Entry
# ---------------------------------------------------------------------------


def main() -> int:
    ap = argparse.ArgumentParser(description="hardened five-config bench harness")
    ap.add_argument("--config", type=int, choices=(1, 2, 3, 4, 5), default=None,
                    help="run one config (default: all five, headline config 3)")
    ap.add_argument("--rows", type=int, default=None)
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--cpu-repeats", type=int, default=3,
                    help="sklearn baseline repeats (medianed — a single "
                    "sample made vs_baseline noisy at the 1.0x boundary)")
    ap.add_argument("--baseline-rows", type=int, default=200_000,
                    help="config 5: sklearn baseline subsample size")
    ap.add_argument("--splitter", choices=("exact", "hist"), default="hist",
                    help="configs 2/3 GBDT splitter. 'hist' (default) is the "
                    "TPU-native design — 256 quantile bins, exact on the "
                    "reference cohort's mostly-binary features, AUC-parity-"
                    "gated vs sklearn's exact enumeration at every size; "
                    "'exact' enumerates every unique midpoint like sklearn")
    ap.add_argument("--budget", type=int, default=1800,
                    help="orchestrator wall-clock budget (s)")
    ap.add_argument("--trace", default="",
                    help="profiler trace dir for config 3 on TPU; the "
                    "orchestrator default is traces/bench_c3 ('' disables)")
    ap.add_argument("--force-cpu", action="store_true",
                    help="skip the TPU probe; run device legs on clean-env CPU")
    ap.add_argument("--detail-out", default=None,
                    help="full-payload JSON file (default: bench_detail.json "
                    "at the repo root; stdout carries a compact summary line)")
    ap.add_argument("--leg", choices=("device", "baseline"), default=None,
                    help=argparse.SUPPRESS)  # internal: subprocess worker mode
    ap.add_argument("--json-out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args.leg:
        # Worker mode: write a result file no matter what happens.
        if args.rows is None:
            args.rows = DEFAULT_ROWS[args.config or 3]
        try:
            rec = device_leg(args) if args.leg == "device" else baseline_leg(args)
        except BaseException as e:  # noqa: BLE001 — the file IS the error channel
            rec = {"error": f"{type(e).__name__}: {e}"}
            import traceback

            traceback.print_exc(file=sys.stderr)
        with open(args.json_out, "w") as f:
            json.dump(rec, f)
        return 0 if "error" not in rec else 1

    try:
        return orchestrate(args)
    except BaseException as e:  # noqa: BLE001 — stdout JSON on every exit path
        import traceback

        traceback.print_exc(file=sys.stderr)
        # Same cap discipline as the summary line — enforced on the
        # SERIALIZED line (JSON escaping can multiply a transcript-bearing
        # error string several-fold past any raw-character cap).
        err = f"{type(e).__name__}: {e}"
        fallback = {
            "metric": "bench_orchestrator_failed",
            "value": 0.0,
            "unit": "s",
            "vs_baseline": 0.0,
        }
        for cap in (SUMMARY_LINE_CAP - 200, 600, 200, 0):
            fallback["error"] = err[:cap]
            line = json.dumps(fallback)
            if len(line) <= SUMMARY_LINE_CAP:
                break
        print(line, flush=True)
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
